"""Chaos plane + self-healing control plane (docs/chaos.md).

Tier-1 pins: the HOROVOD_CHAOS spec grammar and deterministic replay; the
client's broken-latch/reconnect/dedup machinery against stub services
(including the post-timeout desync regression); the controller's
reconnect window (heal and escalate); probe/multi-candidate connect; and
a quick 2-process single-fault matrix on both negotiation cores. The
multi-fault soaks and the full fault grid run under ``slow``.

Each tier-1 test stays well under 10 s (the 870 s tier-1 budget truncates
alphabetically — this file must not starve the tail).
"""

import os
import socket
import threading
import time

import pytest

from horovod_tpu.chaos import (
    ChaosInjector,
    ChaosSpecError,
    parse_chaos_spec,
)
from horovod_tpu.runner.network import (
    BasicClient,
    BasicService,
    ConnectionClosedError,
    CorruptFrameError,
    ReconnectPolicy,
    Wire,
    WireError,
    probe_addresses,
)

pytestmark = pytest.mark.chaos

SECRET = b"chaos-test-secret-chaos-test-sec"

# Small budgets keep failure-path tests quick without loosening semantics.
_FAST = ReconnectPolicy(attempts=3, backoff_s=0.05, max_backoff_s=0.2)


# -- spec grammar -------------------------------------------------------------

def test_chaos_spec_parse_grammar():
    plan = parse_chaos_spec(
        "drop@rank1:msg12,delay@rank0:50ms:every7,corrupt@rank2:msg30,"
        "close@rank1:msg45,refuse@relaunch:2,delay@all:1.5s,"
        "drop@rank0:p0.25,seed:42")
    assert plan.seed == 42
    kinds = [r.kind for r in plan.rules]
    assert kinds == ["drop", "delay", "corrupt", "close", "refuse",
                     "delay", "drop"]
    drop = plan.rules[0]
    assert (drop.rank, drop.ordinal) == (1, 12)
    delay = plan.rules[1]
    assert (delay.rank, delay.every, delay.delay_s) == (0, 7, 0.05)
    refuse = plan.rules[4]
    assert refuse.refusals == 2 and refuse.rank is None
    assert refuse.describe() == "refuse@relaunch:2"
    assert plan.rules[5].rank is None  # scope "all"
    assert plan.rules[5].every == 1  # delay defaults to every request
    assert plan.rules[5].delay_s == 1.5
    assert plan.rules[6].prob == 0.25
    assert parse_chaos_spec("").rules == []  # empty spec = no injection


def test_chaos_spec_parse_errors():
    for bad in ["boom@rank1:msg2",        # unknown kind
                "drop@host1:msg2",        # unknown scope
                "drop@rank1",             # missing trigger
                "drop@rank1:once",        # unknown trigger
                "drop@rank1:msg0",        # ordinals are 1-based
                "delay@rank1:50:every2",  # duration needs a unit
                "drop@rank1:p1.5",        # probability out of range
                "refuse@relaunch:0",      # refusals must be >= 1
                "refuse@rank0:2",         # refuse's only scope is relaunch
                "refuse@all:2",           # (a spec injects what it says)
                "close@relaunch:msg2",    # relaunch scope is refuse-only
                "seed:x"]:
        with pytest.raises(ChaosSpecError):
            parse_chaos_spec(bad)


def test_injector_deterministic_replay():
    """Same spec + seed => bit-identical fault stream over the same
    ordinal sequence (the replay guarantee)."""
    spec = "drop@rank0:p0.2,corrupt@rank0:p0.1,delay@rank0:1ms:every5,seed:9"

    def firing_stream():
        inj = ChaosInjector(parse_chaos_spec(spec), rank=0)
        stream = []
        for _ in range(200):
            inj.begin_request()
            stream.append(tuple(sorted(inj._armed)))
        return stream

    a, b = firing_stream(), firing_stream()
    assert a == b
    assert any(s for s in a), "seeded faults never armed in 200 requests"
    # a different seed moves the probabilistic firings
    other = ChaosInjector(
        parse_chaos_spec(spec.replace("seed:9", "seed:10")), rank=0)
    stream2 = []
    for _ in range(200):
        other.begin_request()
        stream2.append(tuple(sorted(other._armed)))
    assert stream2 != a


def test_injector_rank_scoping():
    plan = parse_chaos_spec("drop@rank1:msg1,corrupt@all:msg1")
    inj0 = ChaosInjector(plan, rank=0)
    inj0.begin_request()
    assert sorted(inj0._armed) == ["corrupt"]  # rank1 clause filtered out
    inj1 = ChaosInjector(plan, rank=1)
    inj1.begin_request()
    assert sorted(inj1._armed) == ["corrupt", "drop"]


# -- client self-healing against a stub service -------------------------------

def _counting_service():
    calls = {"n": 0}

    def handle(req, _sock):
        calls["n"] += 1
        if req == "slow":
            time.sleep(0.5)
        return ("resp", req, calls["n"])

    return BasicService("chaos-stub", handle, secret=SECRET), calls


def _chaos_client(port, spec, timeout_s=5.0):
    inj = ChaosInjector(parse_chaos_spec(spec), rank=0)
    client = BasicClient(("127.0.0.1", port), secret=SECRET,
                         timeout_s=timeout_s, chaos=inj, reconnect=_FAST)
    return client, inj


def test_drop_fault_heals_exactly_once():
    """A dropped response frame reconnects + resends under the same seq;
    the service dedup REPLAYS the stored response — the handler runs
    exactly once per logical request (no double-applied transitions)."""
    svc, calls = _counting_service()
    try:
        client, inj = _chaos_client(svc.port, "drop@rank0:msg2")
        for i in range(4):
            assert client.request(("m", i)) == ("resp", ("m", i), i + 1)
        assert calls["n"] == 4  # exactly-once despite the drop
        assert ("drop", 2) in inj.events
        assert client.reconnects == 1
        client.close()
    finally:
        svc.shutdown()


def test_corrupt_fault_latches_and_heals():
    svc, calls = _counting_service()
    try:
        client, inj = _chaos_client(svc.port, "corrupt@rank0:msg2")
        for i in range(3):
            assert client.request(("c", i)) == ("resp", ("c", i), i + 1)
        assert calls["n"] == 3
        assert ("corrupt", 2) in inj.events and client.reconnects == 1
        client.close()
    finally:
        svc.shutdown()


def test_close_fault_reconnects_with_refused_attempts():
    """close + refuse: the reconnect survives refused dials under
    exponential backoff and the request still completes exactly once."""
    svc, calls = _counting_service()
    try:
        client, inj = _chaos_client(
            svc.port, "close@rank0:msg2,refuse@relaunch:2")
        for i in range(3):
            assert client.request(("k", i)) == ("resp", ("k", i), i + 1)
        assert calls["n"] == 3
        kinds = [k for k, _ in inj.events]
        assert kinds.count("close") == 1 and kinds.count("refuse") == 2
        client.close()
    finally:
        svc.shutdown()


def test_refuse_budget_is_per_attempt_not_per_candidate():
    """Regression: on a multi-candidate (multi-NIC) address,
    ``refuse@relaunch:N`` must burn one refusal per reconnect ATTEMPT —
    each with its own backoff iteration — not one per probed candidate,
    or a 2-NIC world exhausts the whole budget inside attempt 1 and the
    backoff path the fault exists to exercise never runs."""
    svc, _calls = _counting_service()
    backoffs = []

    class _CountingPolicy(ReconnectPolicy):
        def delay(self, attempt):
            backoffs.append(attempt)
            return min(super().delay(attempt), 0.02)

    inj = ChaosInjector(parse_chaos_spec("refuse@relaunch:2"), rank=0)
    addr = ("127.0.0.1", svc.port)
    client = BasicClient({"nic-a": addr, "nic-b": addr}, secret=SECRET,
                         timeout_s=5.0, retry_delay_s=0.05, chaos=inj,
                         reconnect=_CountingPolicy(attempts=6,
                                                   backoff_s=0.01,
                                                   max_backoff_s=0.02))
    try:
        client._broken = True  # as a transport fault would latch it
        assert client.request("n") == ("resp", "n", 1)
        assert client.reconnects == 1
        kinds = [k for k, _ in inj.events]
        assert kinds.count("refuse") == 2
        # refusals landed on reconnect attempts 1 and 2, the connect on
        # attempt 3: two backoff sleeps — per-candidate consumption
        # would burn the whole budget inside attempt 1 and leave one
        assert len(backoffs) == 2
        client.close()
    finally:
        svc.shutdown()


def test_refuse_exhausts_retry_budget_and_escalates():
    """A fault budget beyond the reconnect policy surfaces as an error
    within the bounded backoff budget — never a hang."""
    svc, _calls = _counting_service()
    try:
        client, _inj = _chaos_client(
            svc.port, "close@rank0:msg1,refuse@relaunch:999")
        t0 = time.monotonic()
        with pytest.raises(WireError):
            client.request("doomed")
        assert time.monotonic() - t0 < 10.0
        client.close()
    finally:
        svc.shutdown()


def test_reconnect_into_dead_backlog_bounded_not_hung(monkeypatch):
    """Regression: a reconnect can land in a dying service's kernel
    backlog — the connect SUCCEEDS, but the exiting service never serves
    it — so the re-identify hello on a timeout-less client must be
    time-bounded (``HOROVOD_RECONNECT_HELLO_TIMEOUT_S``): the attempt
    fails and the budget escalates instead of blocking forever in the
    hello read. Also pins the bye path: ``farewell()`` on a broken client
    is a no-op — it must never reconnect (and re-hello into that same
    backlog) just to announce a departure the socket close already
    announces."""
    monkeypatch.setenv("HOROVOD_RECONNECT_HELLO_TIMEOUT_S", "0.3")
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)  # dials land in the backlog; nobody ever accepts
    try:
        client = BasicClient(("127.0.0.1", lsock.getsockname()[1]),
                             secret=SECRET, timeout_s=None,
                             reconnect=ReconnectPolicy(
                                 attempts=2, backoff_s=0.01,
                                 max_backoff_s=0.02))
        client.on_reconnect = lambda c: c.bare_request(("hello", 0, ""))
        client._broken = True  # as a transport fault would latch it
        t0 = time.monotonic()
        with pytest.raises(WireError):
            client.request(("cycle", 0))
        # bounded: 2 dials x 0.3 s hello ceiling + backoff, not forever
        assert time.monotonic() - t0 < 5.0
        assert client.farewell(("bye", 0)) is None and client._broken
        client.close()
    finally:
        lsock.close()


def test_post_timeout_desync_regression():
    """Satellite regression: after a socket timeout the connection may
    hold a partial/late frame; the client must latch broken and force a
    reconnect so the NEXT request can never read the previous response.
    Both hazards covered: a chaos-delayed frame left buffered, and a
    genuinely slow handler whose first invocation is still running when
    the retry arrives (the dedup layer parks and replays — no second
    invocation, no stale pairing)."""
    svc, calls = _counting_service()
    try:
        # hazard 1: response delayed past the socket timeout, frame stays
        # buffered on the old connection
        client, _ = _chaos_client(svc.port, "delay@rank0:900ms:msg1",
                                  timeout_s=0.25)
        assert client.request("a") == ("resp", "a", 1)
        assert client.reconnects == 1
        assert client.request("b") == ("resp", "b", 2)  # not a's stale frame
        client.close()
        # hazard 2: handler slower than the timeout; retry arrives while
        # the first invocation is mid-flight
        client2 = BasicClient(("127.0.0.1", svc.port), secret=SECRET,
                              timeout_s=0.25, reconnect=_FAST)
        assert client2.request("slow") == ("resp", "slow", 3)
        assert client2.request("x") == ("resp", "x", 4)
        assert calls["n"] == 4  # the slow handler ran ONCE
        client2.close()
    finally:
        svc.shutdown()


def test_close_during_reconnect_does_not_park():
    """close() racing a mid-heal request: while ``_reconnect`` is dialing,
    ``_sock`` is None, so close() has no socket to cut through — the
    reconnect must notice the closed latch after the dial and retire the
    fresh socket itself, or the request parks forever in recv on a
    connection close() never saw (a listener backlog accepts the dial;
    nobody ever serves it)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    try:
        client = BasicClient(("127.0.0.1", lsock.getsockname()[1]),
                             secret=SECRET, timeout_s=None,
                             reconnect=_FAST)
        real_dial = client._dial

        def dial_then_teardown(*args, **kwargs):
            sock = real_dial(*args, **kwargs)
            client.close()  # teardown lands while _sock is still None
            return sock

        client._dial = dial_then_teardown
        client._broken = True
        result = {}

        def go():
            try:
                client.request("x")
                result["r"] = "returned"
            except Exception as exc:  # noqa: BLE001 - recording the type
                result["r"] = type(exc).__name__
        t = threading.Thread(target=go, daemon=True)
        t.start()
        t.join(5.0)
        assert result.get("r") == "WireError", (
            f"request on a closed client parked instead of failing: "
            f"{result or 'still running'}")
    finally:
        lsock.close()


def test_matrix_worker_assertion_never_certifies_as_escalation():
    """A rank that dies of its own bit-exact assertion produced WRONG
    RESULTS; the matrix must classify that ``worker-failure`` (accepted by
    no cell), never ``escalated`` — or --allow-escalation sweeps would
    certify silent corruption as a passing escalation."""
    from horovod_tpu.chaos.matrix import _classify_worker_failure
    from horovod_tpu.core.status import RanksAbortedError, failure_record
    from horovod_tpu.runner.run_api import WorkerFailedError

    wrong = failure_record(AssertionError("arrays differ"), "Traceback ...")
    aborted = failure_record(
        RanksAbortedError([1], "rank 1 exited mid-job"), "Traceback ...")
    assert _classify_worker_failure(
        WorkerFailedError([(0, "assert")], records={0: wrong})
    ) == "worker-failure"
    # ...even alongside a genuine abort on another rank
    assert _classify_worker_failure(
        WorkerFailedError([(0, "assert"), (1, "abort")],
                          records={0: wrong, 1: aborted})
    ) == "worker-failure"
    # pure world faults, and old-format peers with no records, escalate
    assert _classify_worker_failure(
        WorkerFailedError([(1, "abort")], records={1: aborted})
    ) == "escalated"
    assert _classify_worker_failure(
        WorkerFailedError([(1, "abort")])) == "escalated"


def test_oversized_response_not_retained_for_replay():
    """The dedup slot must not pin payload-frame-sized responses (a
    departed client's slot survives until LRU displacement — retaining a
    fusion-threshold frame there leaks it for the rest of the job). An
    oversized response is served normally but only a sentinel is
    retained: a resend whose original frame was lost gets a deliberate
    RemoteError (escalation), never a hang; small responses replay
    verbatim."""
    from horovod_tpu.runner.network import (
        BasicService,
        Preserialized,
        RemoteError,
    )

    svc_ref = {}

    def handler(req, _sock):
        if req == "big":
            return Preserialized(
                svc_ref["svc"].wire.frame(b"x" * (2 << 20)))
        return ("small", req)

    svc = BasicService("retain-test", handler, secret=SECRET)
    svc_ref["svc"] = svc
    try:
        client = BasicClient(("127.0.0.1", svc.port), secret=SECRET,
                             reconnect=_FAST)
        assert client.request("big") == b"x" * (2 << 20)
        # a duplicate of that seq (the client's resend after a lost
        # response frame) cannot be replayed — it must fail loudly
        raw = socket.create_connection(("127.0.0.1", svc.port))
        wire = Wire(SECRET)
        wire.write(("#rpc", client._client_id, client._seq - 1, "big"), raw)
        resp = wire.read(raw)
        assert isinstance(resp, RemoteError)
        assert "retention cap" in resp.message
        # small responses stay replayable
        out = client.request("s")
        wire.write(("#rpc", client._client_id, client._seq - 1, "s"), raw)
        assert wire.read(raw) == out
        raw.close()
        client.close()
    finally:
        svc.shutdown()


def test_request_raw_latches_broken_after_timeout():
    """The native binary wire has no dedup: a timed-out request_raw must
    NOT be resent, but the latch must force a fresh connection so the
    next request cannot read the stale late response."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    wire = Wire(SECRET)
    conns = []

    def server():
        # conn 1: delay the first response past the client timeout
        conn, _ = lsock.accept()
        conns.append(conn)
        body = wire.read_raw(conn)
        assert body == b"a"
        time.sleep(0.5)
        try:
            conn.sendall(wire.frame_raw(b"resp-a"))  # lands in a dead buffer
        except OSError:
            pass
        # conn 2: the latched client reconnects; serve normally
        conn, _ = lsock.accept()
        conns.append(conn)
        assert wire.read_raw(conn) == b"b"
        conn.sendall(wire.frame_raw(b"resp-b"))

    t = threading.Thread(target=server, daemon=True)
    t.start()
    client = BasicClient(("127.0.0.1", lsock.getsockname()[1]),
                         secret=SECRET, timeout_s=0.2, reconnect=_FAST)
    with pytest.raises(OSError):
        client.request_raw(b"a")
    assert client._broken
    time.sleep(0.5)  # let the late resp-a land in the dead buffer first
    assert client.request_raw(b"b") == b"resp-b"  # fresh stream, not resp-a
    t.join(timeout=10)
    client.close()
    lsock.close()
    for conn in conns:
        conn.close()


def test_corrupt_frame_error_is_wire_error():
    """Compatibility: HMAC mismatches keep raising WireError (the new
    CorruptFrameError subclass) so existing handlers still catch them."""
    assert issubclass(CorruptFrameError, WireError)
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(2)

    def server():
        # every attempt gets a wrong-secret frame: a wrong key fails the
        # whole retry budget and surfaces as the HMAC diagnostic
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                Wire(SECRET).read(conn)
                conn.sendall(Wire(b"a" * 32).frame(("evil",)))
            except (WireError, OSError):
                pass

    threading.Thread(target=server, daemon=True).start()
    client = BasicClient(("127.0.0.1", lsock.getsockname()[1]),
                         secret=SECRET, timeout_s=2.0,
                         reconnect=ReconnectPolicy(attempts=2,
                                                   backoff_s=0.05))
    with pytest.raises(WireError) as excinfo:
        client.request("x")
    assert "HMAC mismatch" in str(excinfo.value)
    client.close()
    lsock.close()


# -- satellite: probe_addresses / multi-candidate connect ---------------------

def _listener():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(8)
    return sock


def test_probe_addresses_unreachable_candidate_fallback():
    live = _listener()
    dead = _listener()
    dead_addr = dead.getsockname()
    dead.close()  # nothing listens here anymore
    candidates = {"dead": dead_addr, "live": live.getsockname()}
    reachable = probe_addresses(candidates, timeout_s=1.0)
    assert reachable == {"live": live.getsockname()}
    # the client lands on the reachable candidate
    svc = BasicService("probe-stub", lambda req, s: ("ok", req),
                       secret=SECRET)
    client = BasicClient({"dead": dead_addr,
                          "svc": ("127.0.0.1", svc.port)},
                         secret=SECRET, timeout_s=2.0)
    assert client.connected_intf == "svc"
    assert client.request("hi") == ("ok", "hi")
    client.close()
    svc.shutdown()
    live.close()


def test_connect_all_unreachable_error_text():
    gone1, gone2 = _listener(), _listener()
    a1, a2 = gone1.getsockname(), gone2.getsockname()
    gone1.close()
    gone2.close()
    with pytest.raises(WireError) as excinfo:
        BasicClient({"a": a1, "b": a2}, secret=SECRET, attempts=2,
                    retry_delay_s=0.05, timeout_s=0.5)
    msg = str(excinfo.value)
    assert "unable to connect" in msg
    assert str(a1[1]) in msg and str(a2[1]) in msg  # names every candidate
    with pytest.raises(WireError) as excinfo:
        BasicClient({}, secret=SECRET)
    assert "empty candidate" in str(excinfo.value)


def test_reconnect_chooses_surviving_interface():
    """Reconnect re-probes ALL candidates: when the first-connect
    interface dies, the retry must land on another candidate, not spin on
    the dead one."""
    svc_a = BasicService("intf-a", lambda req, s: ("from-a", req),
                        secret=SECRET)
    b_listener = _listener()  # reserves the port, not serving yet
    b_addr = b_listener.getsockname()
    candidates = {"a": ("127.0.0.1", svc_a.port), "b": b_addr}
    b_listener.close()  # during the first connect, only "a" is reachable
    client = BasicClient(candidates, secret=SECRET, timeout_s=2.0,
                         reconnect=ReconnectPolicy(attempts=5,
                                                   backoff_s=0.1))
    assert client.connected_intf == "a"
    assert client.request("one") == ("from-a", "one")
    # interface "a" dies; "b" comes up on its reserved address
    svc_b = BasicService("intf-b", lambda req, s: ("from-b", req),
                         secret=SECRET, port=b_addr[1])
    svc_a.shutdown()
    client._broken = True  # the drop is noticed at the next request
    assert client.request("two") == ("from-b", "two")
    assert client.connected_intf == "b"
    client.close()
    svc_b.shutdown()


# -- controller reconnect window ----------------------------------------------

def _request_of(rank, name):
    from horovod_tpu.ops.messages import DataType, Request, RequestType

    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=(4,))


def test_reconnect_window_heals_dropped_rank():
    """A rank-bound connection that drops and reconnects inside the
    window is forgiven: no abort, the world keeps cycling."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import RequestList

    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg), secret=SECRET,
                                port=0, reconnect_window_s=3.0)
    addr = ("127.0.0.1", service.port)
    c0 = ControllerClient(addr, secret=SECRET, rank=0)
    c1 = ControllerClient(addr, secret=SECRET, rank=1)
    outs = {}
    t = threading.Thread(target=lambda: outs.setdefault(
        0, c0.cycle(0, RequestList(rank=0,
                                   requests=[_request_of(0, "w.t")]))))
    t.start()
    # rank 1's transport dies mid-world; the client latches and heals
    c1._client._sock.close()
    c1._client._broken = True
    time.sleep(0.3)  # let the service notice the EOF and open the window
    outs[1] = c1.cycle(1, RequestList(rank=1,
                                      requests=[_request_of(1, "w.t")]))
    t.join(timeout=20)
    assert set(outs) == {0, 1}
    for out in outs.values():
        assert [n for r in out.responses
                for n in r.tensor_names] == ["w.t"]
    c0.close()
    c1.close()
    service.shutdown()


def test_initial_hello_lost_response_still_binds_rank(monkeypatch):
    """Regression: the re-identify hook must be armed BEFORE the initial
    hello (inside connect_with_hello), not after it returns. A dropped
    hello response heals by reconnect + resend, and the service dedup
    REPLAYS the stored reply without invoking the handler — only the
    hook's bare hello binds the fresh connection, so arming late left a
    healthy rank anonymous, to be spuriously aborted at reconnect-window
    expiry."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import RequestList

    monkeypatch.setenv("HOROVOD_CHAOS", "drop@rank1:msg1")
    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg), secret=SECRET,
                                port=0, reconnect_window_s=5.0)
    addr = ("127.0.0.1", service.port)
    c0 = ControllerClient(addr, secret=SECRET, rank=0)
    c1 = ControllerClient(addr, secret=SECRET, rank=1)
    inj = c1._client._chaos
    assert ("drop", 1) in inj.events and c1._client.reconnects == 1
    time.sleep(0.6)  # let the service notice the retired socket's EOF
    with service._lock:
        assert 1 in service._rank_conns  # the healed connection is bound
        assert not service._pending_reconnect  # the old EOF was anonymous
    # the world is genuinely healthy: a full negotiation cycle completes
    outs = {}
    t = threading.Thread(target=lambda: outs.setdefault(
        0, c0.cycle(0, RequestList(rank=0,
                                   requests=[_request_of(0, "h.t")]))))
    t.start()
    outs[1] = c1.cycle(1, RequestList(rank=1,
                                      requests=[_request_of(1, "h.t")]))
    t.join(timeout=20)
    assert set(outs) == {0, 1}
    c0.close()
    c1.close()
    service.shutdown()


def test_reconnect_window_expiry_escalates_structured():
    """A rank that never returns is declared dead at window expiry — the
    survivor's poisoned cycle names it with the structured abort tag,
    inside a bounded wall-clock."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import RequestList

    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg), secret=SECRET,
                                port=0, reconnect_window_s=1.0)
    addr = ("127.0.0.1", service.port)
    c0 = ControllerClient(addr, secret=SECRET, rank=0)
    c1 = ControllerClient(addr, secret=SECRET, rank=1)
    c1._client.close()  # abrupt death, never reconnects
    t0 = time.monotonic()
    with pytest.raises(WireError) as excinfo:
        c0.cycle(0, RequestList(rank=0, requests=[_request_of(0, "e.t")]))
    elapsed = time.monotonic() - t0
    assert "[aborted ranks: 1]" in str(excinfo.value)
    assert 0.5 < elapsed < 10.0, elapsed  # gated by the window, bounded
    c0.close()
    service.shutdown()


def test_reconnect_window_zero_keeps_immediate_abort():
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import RequestList

    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg), secret=SECRET,
                                port=0, reconnect_window_s=0.0)
    addr = ("127.0.0.1", service.port)
    c0 = ControllerClient(addr, secret=SECRET, rank=0)
    c1 = ControllerClient(addr, secret=SECRET, rank=1)
    c1._client.close()
    t0 = time.monotonic()
    with pytest.raises(WireError) as excinfo:
        c0.cycle(0, RequestList(rank=0, requests=[_request_of(0, "z.t")]))
    assert "[aborted ranks: 1]" in str(excinfo.value)
    assert time.monotonic() - t0 < 5.0
    c0.close()
    service.shutdown()


# -- satellite: structured failure records ------------------------------------

def test_failure_record_structured_attribution():
    from horovod_tpu.core.status import RanksAbortedError, failure_record

    record = failure_record(
        RanksAbortedError([2, 1], "stalled, aborting"), "Traceback ...")
    assert record["format"] == 1
    assert record["aborted_ranks"] == [1, 2]
    assert record["world_fault"] is True
    assert record["error_type"] == "RanksAbortedError"
    user = failure_record(KeyError("bug"), "Traceback ...")
    assert user["aborted_ranks"] is None and user["world_fault"] is False
    # text-tagged reasons still attribute even without a .ranks attr
    tagged = failure_record(
        RuntimeError("shut down [aborted ranks: 3]"), "tb")
    assert tagged["aborted_ranks"] == [3] and tagged["world_fault"]


def test_worker_failed_error_prefers_structured_records():
    from horovod_tpu.elastic.driver import _failed_ranks, _is_world_fault
    from horovod_tpu.runner.run_api import WorkerFailedError

    structured = WorkerFailedError(
        [(0, "Traceback: RanksAbortedError ...")],
        records={0: {"format": 1, "aborted_ranks": [2],
                     "world_fault": True, "traceback": "tb"}})
    assert _failed_ranks(structured) == [2]
    assert _is_world_fault(structured)
    # structured and explicitly NOT a world fault: the record wins even
    # if the traceback text would have matched the old regexes
    user_bug = WorkerFailedError(
        [(1, "user assert mentioning shut down in a string")],
        records={1: {"format": 1, "aborted_ranks": None,
                     "world_fault": False, "traceback": "tb"}})
    assert not _is_world_fault(user_bug)
    assert _failed_ranks(user_bug) == [1]
    # old-format peers (no records): the text fallback still works
    legacy = WorkerFailedError([(0, "shut down [aborted ranks: 2]")])
    assert _failed_ranks(legacy) == [2]
    assert _is_world_fault(legacy)


# -- tier-1 acceptance: 2-process single-fault matrix -------------------------

@pytest.mark.parametrize("native_core", ["0", "1"])
def test_mp_single_fault_drop_heals_bit_exact(native_core):
    """THE chaos contract, on both negotiation cores: a 2-process world
    under drop injection — at a cold negotiation boundary (msg6) and
    through the warm cache-ack steady state (every9) — completes with
    results bit-exact to the fault-free run."""
    from horovod_tpu.chaos.matrix import run_cell

    cell = run_cell("drop@rank1:msg6,drop@rank1:every9",
                    native_controller=0, native_core=int(native_core))
    assert cell["outcome"] == "healed", cell
    r1 = next(r for r in cell["results"] if r["rank"] == 1)
    assert r1["events"], "no fault fired — the cell proved nothing"
    assert r1["reconnects"] >= 1
    # the faults kept firing through response-cache steady state
    assert r1["hit_cycles"] > 0, r1


def test_mp_fault_beyond_budget_escalates_within_deadline():
    """Escalation guarantee: a fault exceeding the retry budget surfaces
    as a structured RanksAbortedError on the healthy rank within the
    stall-shutdown deadline — never a wedge."""
    from horovod_tpu.chaos.matrix import ESCALATION_SPEC, run_cell

    cell = run_cell(ESCALATION_SPEC, native_controller=0, native_core=1,
                    expect_escalation=True)
    assert cell["outcome"] == "escalated", cell
    assert cell["elapsed_s"] < 60.0, cell
    if "results" in cell:
        aborted = [r for r in cell["results"]
                   if r.get("outcome") == "escalated"]
        assert any(1 in r.get("aborted_ranks", []) for r in aborted), cell


# -- slow tier: the full single-fault grid + multi-fault soak -----------------

@pytest.mark.slow
@pytest.mark.parametrize("native_core", ["0", "1"])
@pytest.mark.parametrize("spec_idx", [0, 1, 2, 3])
def test_mp_single_fault_grid_slow(spec_idx, native_core):
    from horovod_tpu.chaos.matrix import DEFAULT_SPECS, run_cell

    cell = run_cell(DEFAULT_SPECS[spec_idx], native_controller=0,
                    native_core=int(native_core))
    assert cell["outcome"] == "healed", cell


@pytest.mark.slow
def test_mp_multi_fault_soak():
    """Every fault kind at once, repeatedly, through warm steady state:
    recovery-or-escalation, never a wedge."""
    from horovod_tpu.chaos.matrix import run_cell

    cell = run_cell(
        "drop@rank1:every7,corrupt@rank1:every11,close@rank1:every13,"
        "delay@rank1:20ms:every5,delay@rank0:10ms:every9,"
        "refuse@relaunch:1,seed:3",
        native_controller=0, native_core=1, steps=16,
        expect_escalation=True)
    assert cell["outcome"] in ("healed", "escalated"), cell


@pytest.mark.slow
def test_mp_native_controller_never_wedges():
    """Under the native (C++) controller the binary wire has no dedup, so
    transport faults escalate instead of healing — the guarantee to pin
    is heal-or-escalate inside the deadline, never a hang."""
    from horovod_tpu.chaos.matrix import run_cell

    for spec in ("drop@rank1:msg6", "delay@rank1:40ms:every5"):
        cell = run_cell(spec, native_controller=1, native_core=1,
                        expect_escalation=True)
        assert cell["outcome"] in ("healed", "escalated"), cell
        assert cell["elapsed_s"] < 90.0, cell
