"""Autograd rules of the three collectives (reference:
``test/test_torch.py:377-428`` allreduce grad, ``:570-611`` allgather grad,
``:768-800`` broadcast grad; TF mirrors at ``test_tensorflow.py:334-367``,
``:592-643``, ``:723-764``).

The reference registers explicit backward rules: allreduce's backward is an
allreduce of the cotangent, allgather's backward is the local slice
(reduce-scatter) of the cotangent, broadcast's backward psums cotangents to
the root (zero elsewhere). In JAX these arise from the transpose rules of
``psum``/``all_gather``/the masked-psum broadcast — these tests pin the
resulting semantics against analytic expectations so a regression in the op
implementations (or a JAX behavior change) is caught.

Loss phrasing — the data-parallel convention, deliberately: each shard
differentiates its LOCAL contribution ``L_i`` to the global loss
``L = sum_i L_i`` and the collective's own transpose supplies the
cross-shard fold, exactly how ``DistributedOptimizer`` produces gradients.
This phrasing is correct under BOTH shard_map tracing regimes: with vma
typing (newer JAX) the cotangent of an axis-invariant collective output is
auto-psummed; under legacy tracing (older JAX, or ``check_vma=False``)
psum's transpose-is-psum supplies the identical fold. The previous
phrasing — wrapping the loss in an extra ``lax.psum`` to spell out "the"
global loss — double-counts by the axis size under the legacy transpose
(each psum transposes to a psum, so the already-folded cotangent gets
folded again): a real test bug, fixed here, that made all five tests fail
by exactly a factor of N on pre-vma JAX.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import spmd
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh

N = 8  # conftest forces an 8-device CPU world


def _run(fn, *args, in_specs, out_specs):
    mesh = data_parallel_mesh()
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))(*args)


def test_allreduce_grad(hvd):
    """L = sum_i w_i . allreduce_sum(x) via local contributions
    L_i = w_i . y => dL/dx_j = sum_i w_i, on every shard (allreduce
    backward == allreduce of cotangents)."""
    x = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)
    w = jnp.arange(1.0, N + 1)[:, None] * jnp.ones((N, 3))  # shard i -> i+1

    def per_shard(x, w):
        def loss(x):
            y = spmd.allreduce(x, DATA_AXIS, average=False)
            return jnp.vdot(w[0], y)

        return jax.grad(loss)(x)

    g = _run(per_shard, x, w,
             in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(DATA_AXIS))
    expected = np.full((N, 3), sum(range(1, N + 1)), np.float32)
    np.testing.assert_allclose(np.asarray(g), expected)


def test_allreduce_mean_grad(hvd):
    """Average variant: backward divides by the world size
    (``torch/mpi_ops.py:110-121`` divides the cotangent for average=True).
    Local contribution L_i = sum(y)/N, so L = sum(y) and dL/dx = 1/N."""
    x = jnp.ones((N, 2), jnp.float32)

    def per_shard(x):
        def loss(x):
            y = spmd.allreduce(x, DATA_AXIS, average=True)
            return y.sum() / N

        return jax.grad(loss)(x)

    g = _run(per_shard, x, in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS))
    np.testing.assert_allclose(np.asarray(g), np.full((N, 2), 1.0 / N),
                               rtol=1e-6)


def test_allgather_grad(hvd):
    """L = sum_i c_i . allgather(x) via L_i = c_i . y => dL/dx_j =
    sum_i c_i sliced to shard j's segment (allgather backward ==
    reduce-scatter of cotangents, the local-slice rule of
    ``test_torch.py:570-611``)."""
    k = 2  # rows per shard
    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.standard_normal((N * k, 3)).astype(np.float32))
    c = jnp.asarray(rng.standard_normal((N, N * k, 3)).astype(np.float32))

    def per_shard(x, c):
        def loss(x):
            y = spmd.allgather(x, DATA_AXIS)  # (N*k, 3) on every shard
            return jnp.vdot(c[0], y)

        return jax.grad(loss)(x)

    g = _run(per_shard, x, c,
             in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(DATA_AXIS))
    c_sum = np.asarray(c).sum(axis=0)  # sum of every shard's cotangent
    np.testing.assert_allclose(np.asarray(g), c_sum, rtol=1e-5)


def test_broadcast_grad(hvd):
    """L = sum_i c_i . broadcast(x, root) via L_i = c_i . y => dL/dx =
    sum_i c_i on the root shard, zero elsewhere (``test_torch.py:768-800``)."""
    root = 2
    x = jnp.ones((N, 4), jnp.float32)
    c = jnp.arange(1.0, N + 1)[:, None] * jnp.ones((N, 4))

    def per_shard(x, c):
        def loss(x):
            y = spmd.broadcast(x[0], root, DATA_AXIS)
            return jnp.vdot(c[0], y)

        return jax.grad(loss)(x)

    g = _run(per_shard, x, c,
             in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(DATA_AXIS))
    g = np.asarray(g)
    total = sum(range(1, N + 1))
    for i in range(N):
        expected = total if i == root else 0.0
        np.testing.assert_allclose(g[i], np.full(4, expected),
                                   err_msg=f"shard {i}")


def test_reducescatter_grad(hvd):
    """reducescatter backward == allgather of cotangents (transpose pair of
    the allgather rule)."""
    k = 2
    x = jnp.ones((N, N * k), jnp.float32)
    c = jnp.arange(1.0, N + 1)[:, None] * jnp.ones((N, k))

    def per_shard(x, c):
        def loss(x):
            y = spmd.reducescatter(x[0], DATA_AXIS)  # (k,) rows per shard
            return jnp.vdot(c[0], y)

        return jax.grad(loss)(x)

    g = _run(per_shard, x, c,
             in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(DATA_AXIS))
    # every shard's x contributes its segment-s rows to shard s's output,
    # so dL/dx is the concatenation of all shards' cotangents — identical
    # on every shard.
    expected = np.repeat(np.arange(1.0, N + 1), k)[None, :].repeat(N, axis=0)
    np.testing.assert_allclose(np.asarray(g), expected)
