"""tools/profile_summary.py turns a captured XPlane profile into the
bottleneck attribution the benchmarks doc needs (round-3 verdict #3). On
TPU captures it reads xprof's hlo_stats (bound_by / HBM bandwidth per op);
this CPU test exercises the capture->parse->rank pipeline end to end via
the raw-trace fallback."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_summary_end_to_end(tmp_path):
    prof_dir = str(tmp_path / "prof")
    capture = f"""
import os
os.environ.pop("JAX_PLATFORMS", None)
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
x = jnp.ones((512, 512))
f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
f(x).block_until_ready()
jax.profiler.start_trace({prof_dir!r})
for _ in range(3):
    x = f(x)
x.block_until_ready()
jax.profiler.stop_trace()
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    cap = subprocess.run([sys.executable, "-c", capture], env=env,
                         capture_output=True, text=True, timeout=300)
    assert cap.returncode == 0, cap.stderr

    out_md = str(tmp_path / "summary.md")
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         prof_dir, "--top", "10", "--out", out_md],
        env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    lines = result.stdout.strip().splitlines()
    summary = json.loads(lines[-1])
    assert summary["total_self_time_us"] > 0
    # the dominant compute op must surface in the ranking
    assert any("dot" in ln for ln in lines), result.stdout
    with open(out_md) as f:
        assert "top 10 ops by self time" in f.read()


def test_profile_summary_missing_dir(tmp_path):
    env = dict(os.environ)
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         str(tmp_path / "nope")],
        env=env, capture_output=True, text=True, timeout=120)
    assert result.returncode != 0
    assert "xplane.pb" in result.stderr


def test_profile_summary_uses_newest_session_only(tmp_path):
    """A retried bench leaves several timestamped capture sessions under
    one profile dir; merging them would double-count every op in the
    attribution artifact — only the newest session may be summarized."""
    import time

    prof_dir = str(tmp_path / "prof")
    capture = f"""
import os, sys
os.environ.pop("JAX_PLATFORMS", None)
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
x = jnp.ones((256, 256))
f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
f(x).block_until_ready()
jax.profiler.start_trace({prof_dir!r})
for _ in range(int(sys.argv[1])):
    x = f(x)
x.block_until_ready()
jax.profiler.stop_trace()
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for reps in ("2", "3"):
        cap = subprocess.run([sys.executable, "-c", capture, reps], env=env,
                             capture_output=True, text=True, timeout=300)
        assert cap.returncode == 0, cap.stderr
        time.sleep(1.1)  # distinct session timestamps/mtimes

    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         prof_dir],
        env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "capture sessions" in result.stderr, result.stderr
    summary = json.loads(result.stdout.strip().splitlines()[-1])
    assert summary["total_self_time_us"] > 0


def test_bench_table_renders_captures(tmp_path):
    """tools/bench_table.py turns watcher captures into the docs table."""
    (tmp_path / "resnet50.json").write_text(json.dumps({
        "metric": "resnet50_synthetic_train_images_per_sec_per_device",
        "value": 1700.0, "unit": "img/s", "vs_baseline": 16.4,
        "live": True, "batch_size": 32, "mfu_pct": 10.8,
        "tflops_per_device": 21.2}) + "\n")
    (tmp_path / "junk.json").write_text("not json\n")
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_table.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr
    assert "ResNet-50, bs 32" in result.stdout
    assert "10.8%" in result.stdout
    empty = tmp_path / "none"
    empty.mkdir()
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_table.py"),
         str(empty)],
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 1
