"""tools/profile_summary.py turns a captured XPlane profile into the
bottleneck attribution the benchmarks doc needs (round-3 verdict #3). On
TPU captures it reads xprof's hlo_stats (bound_by / HBM bandwidth per op);
this CPU test exercises the capture->parse->rank pipeline end to end via
the raw-trace fallback."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The e2e tier drives the REAL converter: tools/profile_summary.py's
# summarize() imports xprof.convert to turn the captured xplane.pb into
# tables. Without the xprof package (this image ships the jax profiler
# but not the converter), every capture summarizes to ModuleNotFoundError
# — the parsing contract is still fully covered by the stubbed-xprof
# fixture tier below, so the e2e tier gates loudly instead of failing on
# an environment it cannot run in.
_NEEDS_XPROF = pytest.mark.skipif(
    importlib.util.find_spec("xprof") is None,
    reason="xprof (the profile converter behind tools/profile_summary.py)"
           " is not installed in this image; the capture->parse pipeline "
           "cannot run — parsing itself is pinned by the stubbed-xprof "
           "fixture tier in this file")


@_NEEDS_XPROF
def test_profile_summary_end_to_end(tmp_path):
    prof_dir = str(tmp_path / "prof")
    capture = f"""
import os
os.environ.pop("JAX_PLATFORMS", None)
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
x = jnp.ones((512, 512))
f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
f(x).block_until_ready()
jax.profiler.start_trace({prof_dir!r})
for _ in range(3):
    x = f(x)
x.block_until_ready()
jax.profiler.stop_trace()
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    cap = subprocess.run([sys.executable, "-c", capture], env=env,
                         capture_output=True, text=True, timeout=300)
    assert cap.returncode == 0, cap.stderr

    out_md = str(tmp_path / "summary.md")
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         prof_dir, "--top", "10", "--out", out_md],
        env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    lines = result.stdout.strip().splitlines()
    summary = json.loads(lines[-1])
    assert summary["total_self_time_us"] > 0
    # the dominant compute op must surface in the ranking
    assert any("dot" in ln for ln in lines), result.stdout
    with open(out_md) as f:
        assert "top 10 ops by self time" in f.read()


def test_profile_summary_missing_dir(tmp_path):
    env = dict(os.environ)
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         str(tmp_path / "nope")],
        env=env, capture_output=True, text=True, timeout=120)
    assert result.returncode != 0
    assert "xplane.pb" in result.stderr


@_NEEDS_XPROF
def test_profile_summary_uses_newest_session_only(tmp_path):
    """A retried bench leaves several timestamped capture sessions under
    one profile dir; merging them would double-count every op in the
    attribution artifact — only the newest session may be summarized."""
    import time

    prof_dir = str(tmp_path / "prof")
    capture = f"""
import os, sys
os.environ.pop("JAX_PLATFORMS", None)
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
x = jnp.ones((256, 256))
f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
f(x).block_until_ready()
jax.profiler.start_trace({prof_dir!r})
for _ in range(int(sys.argv[1])):
    x = f(x)
x.block_until_ready()
jax.profiler.stop_trace()
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for reps in ("2", "3"):
        cap = subprocess.run([sys.executable, "-c", capture, reps], env=env,
                             capture_output=True, text=True, timeout=300)
        assert cap.returncode == 0, cap.stderr
        time.sleep(1.1)  # distinct session timestamps/mtimes

    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "profile_summary.py"),
         prof_dir],
        env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "capture sessions" in result.stderr, result.stderr
    summary = json.loads(result.stdout.strip().splitlines()[-1])
    assert summary["total_self_time_us"] > 0


# -- fixture-table unit tier ---------------------------------------------------
# The end-to-end tests above need a live JAX capture (slow, and the row
# shapes depend on whatever xprof version is installed); the tests below
# pin the PARSING contract itself — gviz table handling, the hlo_stats →
# framework_op_stats fallback, and the final-line-JSON shape — against
# small checked-in fixture tables and a stubbed xprof, so a regression in
# summarize() is attributable without a 300 s capture.


def _load_tool():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_profile_summary_under_test",
        os.path.join(_ROOT, "tools", "profile_summary.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _gviz(cols, rows):
    """Minimal gviz-style {cols, rows} table (the xprof tool output
    shape summarize() parses)."""
    return {"cols": [{"id": c} for c in cols],
            "rows": [{"c": [{"v": v} if v is not None else None
                            for v in row]} for row in rows]}


_HLO_TABLE = _gviz(
    ["hlo_op_name", "category", "total_self_time", "bound_by",
     "occurrences"],
    [["fusion.1", "convolution", 700.0, "hbm", 3],
     ["all-reduce.2", "collective", 200.0, None, 1],
     ["copy.3", "data formatting", 100.0, None, 2]])

_FRAMEWORK_TABLE = _gviz(
    ["operation", "type", "total_self_time_in_us", "occurrences"],
    [["Conv2D", "Conv2D", 60.0, 4],
     ["MatMul", "MatMul", 40.0, 2]])


def _fake_xprof(monkeypatch, tool_data):
    """Install a stub xprof.convert.raw_to_tool_data whose
    xspace_to_tool_data serves canned per-tool JSON (or raises when the
    canned value is an exception)."""
    import types

    def xspace_to_tool_data(paths, tool, _params):
        value = tool_data[tool]
        if isinstance(value, Exception):
            raise value
        return json.dumps(value), None

    r2t = types.ModuleType("xprof.convert.raw_to_tool_data")
    r2t.xspace_to_tool_data = xspace_to_tool_data
    convert = types.ModuleType("xprof.convert")
    convert.raw_to_tool_data = r2t
    xprof = types.ModuleType("xprof")
    xprof.convert = convert
    monkeypatch.setitem(sys.modules, "xprof", xprof)
    monkeypatch.setitem(sys.modules, "xprof.convert", convert)
    monkeypatch.setitem(sys.modules, "xprof.convert.raw_to_tool_data", r2t)


def _capture_dir(tmp_path):
    session = tmp_path / "prof" / "plugins" / "profile" / "2026_08_03"
    session.mkdir(parents=True)
    (session / "host.xplane.pb").write_bytes(b"\x00")  # glob target only
    return str(tmp_path / "prof")


def test_gviz_table_helpers():
    tool = _load_tool()
    nested = [{"not": "a table"}, [_HLO_TABLE], _FRAMEWORK_TABLE]
    tables = list(tool._tables(nested))
    assert tables == [_HLO_TABLE, _FRAMEWORK_TABLE]
    rows = list(tool._rows_as_dicts(_HLO_TABLE))
    assert rows[0]["hlo_op_name"] == "fusion.1"
    assert rows[0]["total_self_time"] == 700.0
    assert rows[1]["bound_by"] is None  # null cells survive as None
    assert tool._pick_time_key(rows[0]) == "total_self_time"
    assert tool._pick_time_key({"name": "x"}) is None


def test_summarize_prefers_hlo_stats(tmp_path, monkeypatch):
    tool = _load_tool()
    _fake_xprof(monkeypatch, {"hlo_stats": _HLO_TABLE,
                              "framework_op_stats": _FRAMEWORK_TABLE})
    lines, summary = tool.summarize(_capture_dir(tmp_path), top=2)
    assert summary["tool"] == "hlo_stats"
    assert summary["total_self_time_us"] == 1000.0
    assert summary["by_category_us"] == {
        "convolution": 700.0, "collective": 200.0, "data formatting": 100.0}
    assert summary["top_op"] == "fusion.1"
    text = "\n".join(lines)
    assert "top 2 ops by self time" in text
    assert "fusion.1" in text and "hbm" in text  # bound_by surfaced


def test_summarize_falls_back_to_framework_op_stats(tmp_path, monkeypatch):
    """hlo_stats failing (CPU traces never populate it) or carrying only
    zero self-time rows must fall through to framework_op_stats."""
    tool = _load_tool()
    zero_hlo = _gviz(["hlo_op_name", "category", "total_self_time"],
                     [["idle", "idle", 0.0]])
    for hlo in (RuntimeError("no hlo_stats in this trace"), zero_hlo):
        _fake_xprof(monkeypatch, {"hlo_stats": hlo,
                                  "framework_op_stats": _FRAMEWORK_TABLE})
        _lines, summary = tool.summarize(_capture_dir(tmp_path), top=5)
        assert summary["tool"] == "framework_op_stats"
        assert summary["total_self_time_us"] == 100.0
        assert summary["top_op"] == "Conv2D"
        import shutil

        shutil.rmtree(tmp_path / "prof")


def test_summarize_missing_captures_raises(tmp_path):
    tool = _load_tool()
    with pytest.raises(FileNotFoundError, match="xplane.pb"):
        tool.summarize(str(tmp_path))


def test_main_final_line_json_contract(tmp_path, monkeypatch, capsys):
    """The LAST stdout line is one JSON object — the contract mechanical
    consumers (bench drivers, the docs table) parse; the human report
    precedes it and --out mirrors the report to a file."""
    tool = _load_tool()
    _fake_xprof(monkeypatch, {"hlo_stats": _HLO_TABLE,
                              "framework_op_stats": _FRAMEWORK_TABLE})
    out_md = str(tmp_path / "summary.md")
    monkeypatch.setattr(sys, "argv", [
        "profile_summary.py", _capture_dir(tmp_path), "--top", "1",
        "--out", out_md])
    tool.main()
    lines = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(lines[-1])
    assert summary["tool"] == "hlo_stats"
    assert summary["total_self_time_us"] == 1000.0
    assert summary["top_op"] == "fusion.1"
    assert set(summary) >= {"profile_dir", "tool", "total_self_time_us",
                            "by_category_us", "top_op"}
    with pytest.raises(ValueError):
        json.loads(lines[-2])  # the report body is NOT the JSON line
    with open(out_md) as f:
        assert "top 1 ops by self time" in f.read()


def test_bench_table_renders_captures(tmp_path):
    """tools/bench_table.py turns watcher captures into the docs table."""
    (tmp_path / "resnet50.json").write_text(json.dumps({
        "metric": "resnet50_synthetic_train_images_per_sec_per_device",
        "value": 1700.0, "unit": "img/s", "vs_baseline": 16.4,
        "live": True, "batch_size": 32, "mfu_pct": 10.8,
        "tflops_per_device": 21.2}) + "\n")
    (tmp_path / "junk.json").write_text("not json\n")
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_table.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr
    assert "ResNet-50, bs 32" in result.stdout
    assert "10.8%" in result.stdout
    empty = tmp_path / "none"
    empty.mkdir()
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_table.py"),
         str(empty)],
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 1
