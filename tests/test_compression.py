"""Quantized-allreduce (EQuARX) data plane: wire dtype, accuracy, policy.

The int8/fp8 codecs change the collective PROGRAM, not just its operand
dtype, so the suite pins three independent properties the way this repo
already pins wire dtypes (tests/test_spmd.py's bf16 scan):

* the lowered/compiled program really carries ``s8`` on the cross-replica
  collective operands (flat AND hierarchical — where ONLY the DCN hop may
  be quantized);
* flat-vs-quantized step results agree within the documented error bound
  (``codec.ERROR_BOUND`` x the across-ranks block absmax);
* the eager plane's per-dtype eligibility is deterministic and a world of
  one round-trips through the quantized program correctly.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.ops import spmd
from horovod_tpu.ops.compression import Compression
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def _shared_block_bound(xs: np.ndarray, codec, n: int) -> np.ndarray:
    """Per-element error bound: across-ranks block absmax x ERROR_BOUND,
    using the codec's own block geometry (``block_layout``)."""
    elems = xs.shape[1]
    block, padded = codec.block_layout(elems, n)
    absmax = np.zeros((n, padded), np.float32)
    absmax[:, :elems] = np.abs(xs)
    bmax = absmax.max(axis=0).reshape(-1, block).max(axis=1)
    return np.repeat(bmax * codec.ERROR_BOUND, block)[:elems]


@pytest.mark.parametrize("codec_name", ["int8", "fp8"])
def test_quantized_allreduce_matches_flat_within_bound(hvd, codec_name):
    codec = Compression.lookup(codec_name)
    mesh = data_parallel_mesh()
    rng = np.random.RandomState(0)
    # per-rank magnitudes spread over 2 decades: block scales must follow
    # the SHARED max, not each rank's own
    xs = (rng.randn(8, 1000).astype(np.float32)
          * np.logspace(-1, 1, 8)[:, None])
    x = jnp.asarray(xs.reshape(-1))

    def step(v):
        return (spmd.quantized_allreduce(v, DATA_AXIS, average=True,
                                         codec=codec),
                jax.lax.pmean(v, DATA_AXIS))

    quant, flat = jax.jit(shard_map(
        step, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=(P(), P()),
        check_vma=False))(x)
    err = np.abs(np.asarray(quant) - np.asarray(flat))
    bound = _shared_block_bound(xs, codec, 8)
    assert (err <= bound + 1e-7).all(), (
        f"{codec_name} error {err.max()} exceeds documented bound "
        f"{bound.max()}")
    # and the sum variant scales consistently
    s = jax.jit(shard_map(
        lambda v: spmd.quantized_allreduce(v, DATA_AXIS, average=False,
                                           codec=codec),
        mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(s), np.asarray(quant) * 8,
                               rtol=1e-6, atol=1e-5)


def test_quantized_allreduce_int_passthrough(hvd):
    """Non-float payloads must reduce exactly (eligibility, SPMD side)."""
    mesh = data_parallel_mesh()
    x = jnp.arange(8 * 16, dtype=jnp.int32)

    out = jax.jit(shard_map(
        lambda v: spmd.quantized_allreduce(v, DATA_AXIS, average=False),
        mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
        check_vma=False))(x)
    expect = np.asarray(x).reshape(8, 16).sum(axis=0)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_quantized_allreduce_empty_leaf(hvd):
    """A zero-element float leaf (empty parameter) must trace, not divide
    by a zero block size."""
    mesh = data_parallel_mesh()
    out = jax.jit(shard_map(
        lambda v: spmd.quantized_allreduce(v, DATA_AXIS, average=False),
        mesh=mesh, in_specs=P(None), out_specs=P(None),
        check_vma=False))(jnp.zeros((0,), jnp.float32))
    assert out.shape == (0,)


def test_int8_dp_step_wire_is_s8(hvd):
    """--int8-allreduce must COMPRESS THE WIRE: the compiled gradient
    reduction carries s8 collective operands (the quantized scatter/gather
    legs), the int8 twin of the bf16 pin in tests/test_spmd.py. Parameters
    stay close to the uncompressed step within the block-relative bound."""
    import optax

    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import ResNet
    from horovod_tpu.models.resnet import ResNetBlock

    mesh = data_parallel_mesh()
    model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10,
                   block_cls=ResNetBlock, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 16, 16, 3),
                          jnp.float32)
    y = jnp.arange(16, dtype=jnp.int32) % 10
    variables = model.init(jax.random.PRNGKey(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt_c = hvd_mod.DistributedOptimizer(optax.sgd(0.01),
                                         axis_name=DATA_AXIS,
                                         compression=Compression.int8)
    step_c = make_dp_train_step(model, opt_c, mesh, axis_name=DATA_AXIS,
                                donate=False, explicit_grad_reduce=True)
    hlo = step_c.lower(params, opt_c.init(params), batch_stats, x,
                       y).compile().as_text()
    s8_collectives = re.findall(
        r"s8\[[^\]]*\][^\n]*?(all-to-all|all-gather)", hlo)
    assert s8_collectives, (
        "int8-compressed DP step compiled without an s8-operand "
        "collective — the quantized wire is not carrying the gradients")
    # the f32 psums that remain must be the BN-stat/loss pmeans and the
    # tiny per-block scale pmax, never a gradient-sized payload; assert
    # no f32 all-to-all exists (the quantized route owns the scatter leg)
    assert not re.search(r"f32\[[^\]]*\][^\n]*all-to-all", hlo)

    opt_p = hvd_mod.DistributedOptimizer(optax.sgd(0.01),
                                         axis_name=DATA_AXIS)
    step_p = make_dp_train_step(model, opt_p, mesh, axis_name=DATA_AXIS,
                                donate=False)
    pc, _, _ = step_c(params, opt_c.init(params), batch_stats, x, y)
    pp, _, _ = step_p(params, opt_p.init(params), batch_stats, x, y)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-3), pc, pp)


def test_hierarchical_quantized_only_dcn_hop(hvd):
    """The EQuARX design point: on the (dcn, ici) route the ICI
    reduce-scatter/all-gather legs stay FULL precision and only the DCN
    hop rides the s8 wire — and the s8 collectives' replica groups span
    the DCN axis, not ICI."""
    from horovod_tpu.parallel.hierarchical import (
        hierarchical_quantized_allreduce,
    )

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dcn", "ici"))
    rng = np.random.RandomState(1)
    xs = rng.randn(8, 512).astype(np.float32)
    x = jnp.asarray(xs.reshape(-1))

    step = jax.jit(shard_map(
        lambda v: hierarchical_quantized_allreduce(v, "dcn", "ici",
                                                   average=True),
        mesh=mesh, in_specs=P(("dcn", "ici")), out_specs=P(),
        check_vma=False))
    hlo = step.lower(x).compile().as_text()

    # device id = 4*dcn + ici: ici groups are contiguous quads, dcn
    # groups are stride-4 pairs (as in test_spmd's hierarchical test).
    # Match INSTRUCTIONS (`= <shape(s)> <op>(`) — operand references like
    # `%reduce-scatter.1` inside fusion lines must not count.
    ICI = "{{0,1,2,3},{4,5,6,7}}"
    DCN = "{{0,4},{1,5},{2,6},{3,7}}"
    rs = [ln for ln in hlo.splitlines()
          if re.search(r"=[^=]*\sreduce-scatter(-start)?\(", ln)]
    assert rs and all(not re.search(r"=\s*\(?s8\[", ln) for ln in rs), (
        "ICI reduce-scatter leg must stay full precision", rs)
    assert any(ICI in ln for ln in rs), ("reduce-scatter not over ici", rs)
    s8_lines = [ln for ln in hlo.splitlines()
                if re.search(r"=\s*\(?[^=]*?s8\[[^\]]*\][^\n]*?"
                             r"(all-to-all|all-gather)(-start)?\(", ln)]
    assert s8_lines, "no s8 collective — the DCN hop is not quantized"
    assert all(DCN in ln for ln in s8_lines), (
        "an s8 collective spans a non-DCN group", s8_lines)

    # numerics: agrees with the flat mean within the bound of ONE
    # quantized hop over the 1/|ici| reduce-scattered shards
    flat = jax.jit(shard_map(
        lambda v: jax.lax.pmean(v, ("dcn", "ici")), mesh=mesh,
        in_specs=P(("dcn", "ici")), out_specs=P(), check_vma=False))(x)
    err = np.abs(np.asarray(step(x)) - np.asarray(flat)).max()
    # coarse but safe: global absmax of the ici-summed shards / 127
    shard_max = np.abs(xs.reshape(2, 4, 512).sum(axis=1)).max() * 4
    assert err <= shard_max * Compression.int8.ERROR_BOUND, err


def test_eager_int8_world_of_one(monkeypatch):
    """Eager-plane eligibility in a world of one: the negotiated codec
    rides the size-1 XLA data plane — f32 payloads take the quantized
    program (round-trip within bound), ineligible dtypes deterministically
    keep the exact full-precision wire."""
    monkeypatch.setenv("HOROVOD_DATA_PLANE", "xla")
    hvd_mod.init()
    try:
        from horovod_tpu.ops.engine import get_engine
        from horovod_tpu.ops.messages import DataType

        plane = get_engine()._plane
        assert plane is not None, "size-1 xla plane did not come up"
        # deterministic per-dtype eligibility mirrors supports()
        assert plane.supports_quantized(DataType.FLOAT32)
        assert not plane.supports_quantized(DataType.INT32)
        assert not plane.supports_quantized(DataType.BOOL)

        rng = np.random.RandomState(2)
        x = rng.randn(3000).astype(np.float32)
        out = hvd_mod.allreduce(x, average=True,
                                compression=Compression.int8)
        # world of one: the quantized program is a quantize->dequantize
        # round trip; block absmax/127 bounds it. The error must also be
        # NONZERO — an exact result means the codec was silently dropped
        # somewhere in negotiation (the native-negotiator regression this
        # test exists to catch), not that the wire is accurate.
        err = np.abs(np.asarray(out) - x)
        bound = _shared_block_bound(x[None, :], Compression.int8, 1)
        assert (err <= bound + 1e-7).all()
        assert err.max() > 0, (
            "int8 allreduce returned the input bit-exactly — the "
            "quantized program did not run")

        xi = np.arange(100, dtype=np.int32)
        outi = hvd_mod.allreduce(xi, average=False,
                                 compression=Compression.int8)
        np.testing.assert_array_equal(np.asarray(outi), xi)  # exact
    finally:
        hvd_mod.shutdown()


def test_codec_negotiation_and_fusion():
    """Control-plane rules (L1): codec mismatches become coordinator
    errors like dtype mismatches, and fusion never merges different
    codecs into one batch."""
    from horovod_tpu.ops.controller import Negotiator
    from horovod_tpu.ops.messages import (
        DataType,
        Request,
        RequestList,
        RequestType,
        ResponseType,
    )

    def req(rank, name, codec):
        return Request(request_rank=rank,
                       request_type=RequestType.ALLREDUCE,
                       tensor_name=name, tensor_type=DataType.FLOAT32,
                       tensor_shape=(4,), codec=codec)

    neg = Negotiator(2, fusion_threshold_bytes=1 << 20)
    neg.add_request_list(RequestList(rank=0, requests=[
        req(0, "a", "int8"), req(0, "b", "none"), req(0, "c", "int8"),
        req(0, "mix", "int8")]))
    neg.add_request_list(RequestList(rank=1, requests=[
        req(1, "a", "int8"), req(1, "b", "none"), req(1, "c", "int8"),
        req(1, "mix", "none")]))
    responses = neg.construct_response_list().responses

    by_names = {tuple(r.tensor_names): r for r in responses}
    # a+c share the int8 codec but b ("none") sits between them in
    # arrival order, so fusion must produce [a], [b], [c] — never a
    # mixed-codec batch
    for names, resp in by_names.items():
        if "mix" in names:
            assert resp.response_type == ResponseType.ERROR
            assert "compression codec" in resp.error_message.lower()
        else:
            codecs = {"a": "int8", "b": "none", "c": "int8"}
            assert len({codecs[n] for n in names}) == 1, names
            assert resp.tensor_codec == codecs[names[0]]


def test_native_negotiator_codec_stamping():
    """The C++ negotiation core predates the codec field; its Python
    wrapper must stamp negotiated codecs onto responses, keep fused
    batches codec-pure, and turn cross-rank mismatches into coordinator
    ERRORs — the same contract as the Python Negotiator."""
    from horovod_tpu import cc
    from horovod_tpu.ops.messages import (
        DataType,
        Request,
        RequestList,
        RequestType,
        ResponseType,
    )

    if not cc.available():
        pytest.skip("native core not built")

    def req(rank, name, codec):
        return Request(request_rank=rank,
                       request_type=RequestType.ALLREDUCE,
                       tensor_name=name, tensor_type=DataType.FLOAT32,
                       tensor_shape=(4,), codec=codec)

    neg = cc.NativeNegotiator(2, fusion_threshold_bytes=1 << 20)
    for rank in (0, 1):
        neg.add_request_list(RequestList(rank=rank, requests=[
            req(rank, "q", "int8"), req(rank, "p", "none"),
            req(rank, "mix", "int8" if rank == 0 else "none")]))
    responses = neg.construct_response_list().responses
    by_name = {}
    for r in responses:
        for n in r.tensor_names:
            by_name[n] = r
    assert by_name["q"].tensor_codec == "int8"
    assert by_name["p"].tensor_codec == "none"
    # never fused across codecs
    assert set(by_name["q"].tensor_names) != set(by_name["p"].tensor_names)
    assert by_name["mix"].response_type == ResponseType.ERROR
    assert "codec" in by_name["mix"].error_message.lower()


def test_compression_env_knob(monkeypatch):
    """HOROVOD_COMPRESSION resolves the default codec (core/config.py)."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.optimizers import _resolve_compression

    monkeypatch.setenv("HOROVOD_COMPRESSION", "int8")
    assert Config.from_env().compression == "int8"
    assert _resolve_compression(None) is Compression.int8
    # explicit argument always wins over the env
    assert _resolve_compression(Compression.bf16) is Compression.bf16
    monkeypatch.delenv("HOROVOD_COMPRESSION")
    assert _resolve_compression(None) is Compression.none
    with pytest.raises(ValueError):
        Compression.lookup("int4")
