"""Subprocess worker for multi-process eager collective tests.

The analog of running a reference test file under ``mpirun -np N``
(SURVEY §4): the same assertions, but rank/size/controller address come
from the launcher env. Exits 0 on success; any assertion error exits
non-zero and the parent test fails.
"""

import os
import sys

# Workers run on CPU with a single device each (one process == one rank,
# exactly the reference's process model). The TPU plugin prepends itself to
# JAX_PLATFORMS, so pin the platform via config before any backend starts —
# N worker processes must never contend for the single real chip.
os.environ.pop("JAX_PLATFORMS", None)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# With HOROVOD_TEST_JAX_COORD set, workers form a real multi-process JAX
# world (gloo-backed CPU collectives) so the eager XLA data plane runs the
# same cross-process compiled-collective path it uses on TPU pods.
_coord = os.environ.get("HOROVOD_TEST_JAX_COORD")
if _coord:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        _coord,
        num_processes=int(os.environ["HOROVOD_SIZE"]),
        process_id=int(os.environ["HOROVOD_RANK"]))

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import horovod_tpu as hvd  # noqa: E402


def main() -> None:
    scenario = sys.argv[1]
    if scenario.startswith("subset"):
        return _subset_scenario(scenario)
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    assert size == int(os.environ["HOROVOD_SIZE"])
    assert rank == int(os.environ["HOROVOD_RANK"])

    if scenario == "allreduce":
        x = np.full((8, 4), float(rank + 1), dtype=np.float32)
        out = hvd.allreduce(x, average=False, name="mp.sum")
        expected = sum(range(1, size + 1))
        np.testing.assert_array_equal(np.asarray(out), expected)
        avg = hvd.allreduce(x, average=True, name="mp.avg")
        np.testing.assert_allclose(np.asarray(avg), expected / size)
        if isinstance(avg, np.ndarray):
            avg += 0.0  # results must be writable on every data plane
                        # (the torch front-end mutates them in place)

    elif scenario == "fused":
        tensors = [np.full((50,), float(rank + i), np.float32)
                   for i in range(10)]
        handles = [hvd.allreduce_async(t, average=False, name=f"mp.fused.{i}")
                   for i, t in enumerate(tensors)]
        for i, h in enumerate(handles):
            out = hvd.synchronize(h)
            expected = sum(r + i for r in range(size))
            np.testing.assert_array_equal(np.asarray(out), expected)

    elif scenario == "jax_fused":
        # Device-resident submissions: jax.Arrays fuse and reduce via the
        # on-chip pack→psum→unpack path on the XLA plane (zero host
        # transfers), or convert lazily on the host plane — values and
        # round-trip types must match on both.
        import jax.numpy as jnp

        tensors = [jnp.full((40,), float(rank + i), jnp.float32)
                   for i in range(8)]
        handles = [hvd.allreduce_async(t, average=False, name=f"mp.jaxf.{i}")
                   for i, t in enumerate(tensors)]
        for i, h in enumerate(handles):
            out = hvd.synchronize(h)
            assert isinstance(out, jax.Array), type(out)
            expected = sum(r + i for r in range(size))
            np.testing.assert_array_equal(np.asarray(out), expected)
        # averaging of a device result happens on device
        avg = hvd.allreduce(jnp.full((8,), float(rank + 1)), average=True,
                            name="mp.jax.avg")
        np.testing.assert_allclose(np.asarray(avg),
                                   sum(range(1, size + 1)) / size)
        # a mixed numpy+jax cycle falls back to one host-packed buffer;
        # both callers still get their framework type back
        hj = hvd.allreduce_async(jnp.arange(6, dtype=jnp.float32),
                                 average=False, name="mp.jax.mix.j")
        hn = hvd.allreduce_async(np.arange(6, dtype=np.float32),
                                 average=False, name="mp.jax.mix.n")
        outj, outn = hvd.synchronize(hj), hvd.synchronize(hn)
        assert isinstance(outj, jax.Array) and isinstance(outn, np.ndarray)
        np.testing.assert_array_equal(np.asarray(outj),
                                      np.arange(6, dtype=np.float32) * size)
        np.testing.assert_array_equal(outn,
                                      np.arange(6, dtype=np.float32) * size)
        # bf16 — the MXU-native wire — must survive the trip
        hb = hvd.allreduce(jnp.ones((16,), jnp.bfloat16), average=False,
                           name="mp.jax.bf16")
        np.testing.assert_array_equal(
            np.asarray(hb, dtype=np.float32), float(size))
        # device-resident ragged allgather
        g = hvd.allgather(jnp.full((rank + 1, 3), float(rank)),
                          name="mp.jax.gather")
        assert isinstance(g, jax.Array), type(g)
        np.testing.assert_array_equal(
            np.asarray(g),
            np.concatenate([np.full((r + 1, 3), float(r), np.float32)
                            for r in range(size)]))
        # device-resident broadcast: non-root Inf garbage must not leak,
        # narrow int dtypes must widen losslessly and cast back
        root = size - 1
        y = (jnp.full((5,), 7.0) if rank == root
             else jnp.full((5,), jnp.inf))
        b = hvd.broadcast(y, root_rank=root, name="mp.jax.bcast")
        assert isinstance(b, jax.Array), type(b)
        np.testing.assert_array_equal(np.asarray(b), 7.0)
        bi = hvd.broadcast(jnp.arange(4, dtype=jnp.int8) + rank,
                           root_rank=0, name="mp.jax.bcast.i8")
        assert np.asarray(bi).dtype == np.int8
        np.testing.assert_array_equal(np.asarray(bi),
                                      np.arange(4, dtype=np.int8))

    elif scenario == "allgather":
        # ragged first dims: rank r contributes r+1 rows of value r
        x = np.full((rank + 1, 3), float(rank), dtype=np.float32)
        out = np.asarray(hvd.allgather(x, name="mp.gather"))
        expected = np.concatenate(
            [np.full((r + 1, 3), float(r), np.float32) for r in range(size)])
        np.testing.assert_array_equal(out, expected)

    elif scenario == "broadcast":
        root = size - 1
        x = np.full((4,), float(rank * 10 + 5), dtype=np.float32)
        out = np.asarray(hvd.broadcast(x, root_rank=root, name="mp.bcast"))
        np.testing.assert_array_equal(out, float(root * 10 + 5))
        # non-root buffer contents are ignored — even Inf/NaN garbage
        # (uninitialized params about to be overwritten) must not leak into
        # the result on any data plane
        y = (np.full((3,), 7.0, np.float32) if rank == root
             else np.full((3,), np.inf, np.float32))
        out2 = np.asarray(hvd.broadcast(y, root_rank=root, name="mp.bcast2"))
        np.testing.assert_array_equal(out2, 7.0)

    elif scenario == "mismatch":
        # rank-dependent shapes must error on ALL ranks
        # (reference: test_torch.py:270-366)
        x = np.ones((rank + 2, 2), dtype=np.float32)
        try:
            hvd.allreduce(x, name="mp.mismatch")
        except hvd.HorovodInternalError as exc:
            assert "Mismatched allreduce tensor shapes" in str(exc)
        else:
            raise AssertionError("expected coordinator error on all ranks")

    elif scenario == "torch_grad":
        # Autograd rules for the collectives across real ranks (reference
        # ``test_torch.py:377-428``): backward of allreduce is allreduce,
        # allgather backward slices the summed gradient, broadcast sends
        # all gradient to the root.
        import torch

        import horovod_tpu.torch as hvd_torch

        x = torch.arange(4, dtype=torch.float32, requires_grad=True)
        w = torch.full((4,), float(rank + 1))
        y = hvd_torch.allreduce(x, average=False, name="g.ar")
        (y * w).sum().backward()
        # grad_output = w; backward allreduce sums w over ranks
        np.testing.assert_array_equal(
            x.grad.numpy(), np.full(4, float(sum(range(1, size + 1)))))

        g = torch.ones(rank + 1, 2, requires_grad=True)  # ragged rows
        out = hvd_torch.allgather(g, name="g.gather")
        (out * float(rank + 1)).sum().backward()
        # grad_output = (rank+1)*ones per rank; summed over ranks then this
        # rank keeps its own row block
        np.testing.assert_array_equal(
            g.grad.numpy(),
            np.full((rank + 1, 2), float(sum(range(1, size + 1)))))

        b = torch.ones(3, requires_grad=True)
        root = size - 1
        bout = hvd_torch.broadcast(b, root_rank=root, name="g.bcast")
        (bout * float(rank + 1)).sum().backward()
        expected = (float(sum(range(1, size + 1)))
                    if rank == root else 0.0)
        np.testing.assert_array_equal(b.grad.numpy(), np.full(3, expected))

    elif scenario == "torch_unused":
        # Rank-dependent unused parameters (reference
        # ``test_force_allreduce``): a rank whose backward never touched a
        # param must still join that param's allreduce with zeros —
        # skipping a collective the peers wait on would deadlock — and all
        # ranks must end the step with identical weights.
        import torch

        import horovod_tpu.torch as hvd_torch

        torch.manual_seed(5)
        l1, l2 = torch.nn.Linear(4, 4), torch.nn.Linear(4, 2)
        named = ([("l1." + k, v) for k, v in l1.named_parameters()] +
                 [("l2." + k, v) for k, v in l2.named_parameters()])
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD([p for _, p in named], lr=0.1),
            named_parameters=named)
        hvd_torch.broadcast_parameters(dict(named), root_rank=0)
        x = torch.full((3, 4), float(rank + 1))
        loss = (l2(l1(x)).sum() if rank == 0 else l1(x).sum())
        loss.backward()
        opt.step()  # must not hang; rank>0 joins l2's allreduce with zeros
        w = torch.cat([p.detach().reshape(-1) for _, p in named])
        gathered = hvd_torch.allgather(w.reshape(1, -1),
                                       name="unused.check")
        for r in range(1, size):
            np.testing.assert_allclose(gathered[r].numpy(),
                                       gathered[0].numpy(), rtol=1e-6)

    elif scenario == "torch":
        import torch

        import horovod_tpu.torch as hvd_torch

        torch.manual_seed(1234)  # same init on all ranks
        model = torch.nn.Linear(4, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=1.0),
            named_parameters=model.named_parameters())
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        before = {k: v.clone() for k, v in model.state_dict().items()}

        # rank-dependent input -> rank-dependent grads; step must apply the
        # world-averaged gradient on every rank
        x = torch.full((8, 4), float(rank + 1))
        loss = model(x).sum()
        loss.backward()
        opt.step()

        # replicate the expected mean gradient locally
        ref = torch.nn.Linear(4, 2)
        ref.load_state_dict(before)
        grads = []
        for r in range(size):
            ref.zero_grad()
            loss_r = ref(torch.full((8, 4), float(r + 1))).sum()
            loss_r.backward()
            grads.append([p.grad.clone() for p in ref.parameters()])
        mean_grads = [sum(gs) / size for gs in zip(*grads)]
        for p, g, b in zip(model.parameters(), mean_grads,
                           [before["weight"], before["bias"]]):
            np.testing.assert_allclose(
                p.detach().numpy(), (b - 1.0 * g).numpy(), rtol=1e-5)

        # torch eager ops incl. bf16 wire
        t = torch.full((4,), float(rank), dtype=torch.bfloat16)
        out = hvd_torch.allreduce(t, average=True, name="mp.torch.bf16")
        assert out.dtype == torch.bfloat16
        np.testing.assert_allclose(out.float().numpy(),
                                   sum(range(size)) / size, rtol=1e-2)

    elif scenario == "torch_state":
        # divergent optimizer state: root restored (momentum populated),
        # workers fresh (state empty) — must NOT deadlock, and workers must
        # adopt root's buffers
        import torch

        import horovod_tpu.torch as hvd_torch

        torch.manual_seed(7)
        model = torch.nn.Linear(3, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.5, momentum=0.9)
        if rank == 0:
            model(torch.ones(4, 3)).sum().backward()
            opt.step()  # populates momentum buffers on root only
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        state = opt.state_dict()["state"]
        assert len(state) > 0, "workers did not adopt root's state"
        for pstate in state.values():
            buf = pstate.get("momentum_buffer")
            assert buf is not None and float(buf.abs().sum()) > 0

    elif scenario == "tf":
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd_tf

        # eager ops: rank-dependent values
        t = tf.fill((4,), float(rank + 1))
        out = hvd_tf.allreduce(t, average=False, name="mp.tf.sum")
        np.testing.assert_array_equal(out.numpy(),
                                      float(sum(range(1, size + 1))))

        # DistributedGradientTape: rank-dependent grads must average
        v = tf.Variable([1.0, 2.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * float(rank + 1))
        tape = hvd_tf.DistributedGradientTape(tape)
        grads = tape.gradient(loss, [v])
        mean_scale = sum(r + 1 for r in range(size)) / size
        np.testing.assert_allclose(grads[0].numpy(), mean_scale, rtol=1e-6)

        # broadcast_variables: workers adopt root's value
        var = tf.Variable([float(rank * 10)] * 3)
        hvd_tf.broadcast_variables([var], root_rank=0)
        np.testing.assert_array_equal(var.numpy(), 0.0)

        # sparse IndexedSlices -> 2x allgather path
        s = tf.IndexedSlices(values=tf.fill((1, 2), float(rank + 1)),
                             indices=tf.constant([rank]),
                             dense_shape=tf.constant([size, 2]))
        rs = hvd_tf.allreduce(s, average=False, name="mp.tf.sparse")
        assert rs.values.shape[0] == size

    elif scenario == "tf_grad":
        # TF collective backward rules across real ranks — the tf twin of
        # torch_grad (reference gradient registrations mpi_ops.py:94-183).
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd_tf

        x = tf.Variable(np.arange(4, dtype=np.float32))
        w = tf.constant(float(rank + 1))
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(
                hvd_tf.allreduce(x, average=False, name="g.ar") * w)
        total = float(sum(range(1, size + 1)))
        np.testing.assert_array_equal(tape.gradient(loss, x).numpy(),
                                      np.full(4, total))

        g = tf.Variable(np.ones((rank + 1, 2), np.float32))  # ragged rows
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(
                hvd_tf.allgather(g, name="g.gather") * float(rank + 1))
        np.testing.assert_array_equal(tape.gradient(loss, g).numpy(),
                                      np.full((rank + 1, 2), total))

        b = tf.Variable(np.ones(3, np.float32))
        root = size - 1
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(
                hvd_tf.broadcast(b, root_rank=root,
                                 name="g.bcast") * float(rank + 1))
        expected = total if rank == root else 0.0
        np.testing.assert_array_equal(tape.gradient(loss, b).numpy(),
                                      np.full(3, expected))

    elif scenario == "tf_keras":
        import keras
        import tensorflow as tf  # noqa: F401

        import horovod_tpu.tensorflow.keras as hvd_keras

        np.random.seed(100 + rank)  # rank-divergent init: broadcast must fix
        keras.utils.set_random_seed(100 + rank)
        X = np.random.randn(32, 4).astype(np.float32)
        Y = np.sum(X, axis=1, keepdims=True)
        model = keras.Sequential([keras.layers.Dense(1)])
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.05))
        model.compile(optimizer=opt, loss="mse")
        cbs = [hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
               hvd_keras.callbacks.MetricAverageCallback()]
        model.fit(X, Y, batch_size=16, epochs=2, callbacks=cbs, verbose=0)
        # after the broadcast callback + averaged gradients, weights must be
        # bitwise identical on all ranks
        w = np.concatenate([np.ravel(v.numpy()) for v in model.weights])
        gathered = np.asarray(hvd_keras.allgather(
            w.reshape(1, -1), name="mp.keras.weights"))
        for r in range(size):
            np.testing.assert_array_equal(gathered[r], gathered[0])

    elif scenario == "cache_steady":
        # Steady-state negotiation bypass (docs/response-cache.md): the
        # same tensor set every step must turn into cache-bit cycles after
        # the first negotiated step, with BIT-EXACT results either way.
        # The parent runs this scenario with the cache on and with
        # HOROVOD_CACHE_CAPACITY=0 and compares the CACHE-HASH lines.
        import hashlib

        from horovod_tpu.ops.engine import get_engine

        digest = hashlib.sha256()
        steps, n_tensors = 12, 5
        for step in range(steps):
            handles = [hvd.allreduce_async(
                np.full((64,), float(rank * 17 + i) + 0.37 * i, np.float32),
                average=False, name=f"cs.{i}") for i in range(n_tensors)]
            for i, h in enumerate(handles):
                out = np.asarray(hvd.synchronize(h))
                # float32 accumulation in rank order — exactly the
                # coordinator's host combine, so equality is bitwise
                acc = np.zeros((64,), np.float32)
                for r in range(size):
                    acc = acc + np.full(
                        (64,), float(r * 17 + i) + 0.37 * i, np.float32)
                np.testing.assert_array_equal(out, acc)
                digest.update(out.tobytes())
        stats = get_engine().cache_stats()
        if int(os.environ.get("HOROVOD_CACHE_CAPACITY", "1") or 0) > 0:
            # idle ticks also ride the bitvector, so hit_cycles alone is
            # weak; miss_cycles < steps is the real claim — at least one
            # whole STEP negotiated through the bypass
            assert stats["miss_cycles"] >= 1, stats
            assert stats["miss_cycles"] < steps, stats
            assert stats["hit_cycles"] > 0, stats
            assert stats["entries"] >= 1, stats
        else:
            assert stats["capacity"] == 0, stats
            assert stats["hit_cycles"] == 0 == stats["miss_cycles"], stats
        print(f"CACHE-HASH {digest.hexdigest()}", flush=True)

    elif scenario == "cache_stall":
        # Acceptance: a stall injected DURING an all-hit steady state must
        # still escalate to RanksAbortedError within
        # HOROVOD_STALL_SHUTDOWN_TIME_S — the bypass keeps the
        # coordinator's stall check and escalation deadline running (a
        # cache hit must never mask a dead rank). Parent env: warning 1s,
        # shutdown 2s, cache on, Python controller.
        import time

        from horovod_tpu.ops.engine import get_engine

        engine = get_engine()
        for _ in range(3):  # build the warm steady state
            hvd.allreduce(np.ones((16,), np.float32), average=False,
                          name="cst.steady")
        trap = None
        if rank == 0:
            # planted stall: rank 1 never submits this name
            trap = hvd.allreduce_async(np.ones((4,), np.float32),
                                       average=False, name="cst.trap")
        t0 = time.monotonic()
        aborted = False
        try:
            while time.monotonic() - t0 < 20.0:
                hvd.allreduce(np.full((16,), 2.0, np.float32),
                              average=False, name="cst.steady")
                time.sleep(0.005)
        except (hvd.RanksAbortedError, RuntimeError) as exc:
            assert "shut down" in str(exc), exc
            aborted = True
        assert aborted, "stall never escalated during the warm steady state"
        assert time.monotonic() - t0 < 15.0
        stats = engine.cache_stats()
        assert stats["hit_cycles"] > 0, (
            "steady state never reached the bypass; this scenario would "
            "not be testing stall-under-hit at all", stats)
        if rank == 0:
            try:
                hvd.synchronize(trap)
            except hvd.RanksAbortedError as exc:
                assert exc.ranks == [1], exc.ranks
            else:
                raise AssertionError("trap handle did not carry the abort")

    elif scenario == "stall_abort":
        # Abort-instead-of-hang (HOROVOD_STALL_SHUTDOWN_TIME_S): rank 0
        # submits a tensor the other rank NEVER submits. The reference
        # behavior is an infinite hang behind a stall warning; with the
        # shutdown deadline set (parent env: warning 1s, shutdown 2s) the
        # coordinator escalates into a structured world abort and rank 0
        # raises RanksAbortedError naming the missing rank — well before
        # the parent's harness timeout.
        import time

        from horovod_tpu.ops.engine import get_engine

        engine = get_engine()
        if rank == 0:
            t0 = time.monotonic()
            try:
                hvd.allreduce(np.ones((4,), np.float32), average=False,
                              name="sa.trap")
            except hvd.RanksAbortedError as exc:
                assert exc.ranks == [1], exc.ranks
                assert "shut down" in str(exc), exc
            else:
                raise AssertionError(
                    "expected RanksAbortedError from the stall deadline")
            assert time.monotonic() - t0 < 20.0
        else:
            # the permanently-absent rank: keep cycling (the engine loop
            # does) but never submit sa.trap; the escalated shutdown must
            # stop this engine too instead of leaving it parked
            assert engine._stopped.wait(25.0), \
                "absent rank's engine not stopped by the escalation"

    elif scenario == "object_edge":
        # broadcast_object edge cases: None payload, empty bytes, a blob
        # far above the (parent-shrunk) fusion threshold, and an exact
        # pickle round-trip on non-root ranks.
        import pickle

        out = hvd.broadcast_object(None if rank == 0 else "junk",
                                   root_rank=0, name="oe.none")
        assert out is None, out
        out = hvd.broadcast_object(b"" if rank == 0 else None,
                                   root_rank=0, name="oe.empty")
        assert out == b"", out
        out = hvd.broadcast_object([] if rank == 0 else None,
                                   root_rank=0, name="oe.emptylist")
        assert out == [], out
        blob = bytes(range(256)) * 4096  # 1 MiB >> threshold
        out = hvd.broadcast_object({"blob": blob} if rank == 0 else None,
                                   root_rank=0, name="oe.big")
        assert out["blob"] == blob
        obj = {"a": [1, 2, {"b": (3.5, "s")}], "t": ("x", None),
               "arr": np.arange(7, dtype=np.int16)}
        out = hvd.broadcast_object(obj if rank == 0 else None,
                                   root_rank=0, name="oe.exact")
        # non-root ranks must see a payload that round-trips pickle
        # exactly (same bytes as root's serialization)
        ref = {**obj, "arr": obj["arr"]}
        assert pickle.dumps(out) == pickle.dumps(ref)
        np.testing.assert_array_equal(out["arr"], obj["arr"])

    elif scenario == "stall":
        # rank 0 submits immediately; rank 1 delays past the stall window so
        # the coordinator must print the stall warning naming the missing
        # rank (CheckForStalledTensors, operations.cc:1625-1672) — then the
        # late submission still completes correctly.
        import time

        x = np.ones((4,), dtype=np.float32)
        if rank == 1:
            time.sleep(3.0)
        out = hvd.allreduce(x, average=False, name="stalled_tensor")
        np.testing.assert_array_equal(np.asarray(out), float(size))

    elif scenario == "autotune":
        # end-to-end autotune on a multi-process world: sustained eager
        # traffic must drive the coordinator's tuner (knob movement is
        # asserted by the parent via HOROVOD_AUTOTUNE_LOG) while results
        # stay correct and the tuned cycle time propagates to workers
        for batch in range(40):
            tensors = [np.full((500,), float(rank + i), np.float32)
                       for i in range(6)]
            handles = [hvd.allreduce_async(t, average=False,
                                           name=f"at.{batch}.{i}")
                       for i, t in enumerate(tensors)]
            for i, h in enumerate(handles):
                out = np.asarray(hvd.synchronize(h))
                np.testing.assert_array_equal(
                    out, float(sum(r + i for r in range(size))))

    elif scenario == "peer_death":
        # Failure detection under load (reference semantics: an exception or
        # exit on one rank shuts the whole world down,
        # ``operations.cc:1942-1957``): the last rank dies abruptly with
        # tensors in flight; every survivor must unblock with
        # SHUT_DOWN_ERROR well inside the stall window instead of hanging.
        import time

        victim = size - 1
        # Barrier: the kill must hit a fully-formed world mid-stream, not a
        # rank still inside init (that is a different failure, surfaced as
        # an init error).
        hvd.allreduce(np.ones((4,), np.float32), average=False,
                      name="pd.barrier")
        if rank == victim:
            # Same shapes as the survivors: under heavy CPU load the
            # victim's cycle can ship these before the _exit lands, and a
            # shape mismatch would then surface as a coordinator ERROR
            # instead of the death-abort this scenario pins.
            for i in range(3):
                hvd.allreduce_async(np.ones((256,), np.float32),
                                    average=False, name=f"pd.{i}")
            os._exit(3)  # no shutdown message, no atexit — a real crash
        handles = [hvd.allreduce_async(np.full((256,), float(rank),
                                               np.float32),
                                       average=False, name=f"pd.{i}")
                   for i in range(8)]
        t0 = time.monotonic()
        try:
            for h in handles:
                hvd.synchronize(h)
        except hvd.HorovodInternalError as exc:
            assert "shut down" in str(exc), exc
        else:
            raise AssertionError("expected SHUT_DOWN_ERROR after peer death")
        elapsed = time.monotonic() - t0
        assert elapsed < 30.0, f"unblocked only after {elapsed:.1f}s"

    elif scenario == "peer_death_xla":
        # The realistic TPU failure mode: a rank dies while its peers are
        # blocked INSIDE a compiled XLA collective (gloo/ICI — not a TCP
        # recv the controller can poison). The controller attributes the
        # death and pushes the abort over the watch channel; survivors'
        # engines abandon the stuck collective (``_DevicePlaneWorker``)
        # and every outstanding handle fails with SHUT_DOWN_ERROR.
        import time

        import jax.numpy as jnp

        from horovod_tpu.ops.engine import get_engine

        victim = size - 1
        hvd.allreduce(np.ones((4,), np.float32), average=False,
                      name="px.barrier")
        engine = get_engine()
        assert engine._plane is not None, "scenario requires the XLA plane"
        if rank == victim:
            # Deterministic timing: this rank negotiates the collective
            # (so every peer will issue the compiled psum) but dies at
            # execution time, exactly when the survivors are inside it.
            engine._plane.allreduce_onchip = \
                lambda *a, **k: os._exit(3)  # type: ignore[method-assign]
            hvd.allreduce_async(jnp.ones((64,), jnp.float32),
                                average=False, name="px.trap")
            time.sleep(60.0)  # the engine executes + exits from its loop
            raise AssertionError("victim failed to die")
        h = hvd.allreduce_async(jnp.full((64,), float(rank), jnp.float32),
                                average=False, name="px.trap")
        t0 = time.monotonic()
        try:
            hvd.synchronize(h)
        except hvd.HorovodInternalError as exc:
            assert "shut down" in str(exc), exc
        else:
            raise AssertionError(
                "expected SHUT_DOWN_ERROR after peer death inside a "
                "compiled collective")
        elapsed = time.monotonic() - t0
        assert elapsed < 30.0, f"unblocked only after {elapsed:.1f}s"
        # Survivors exit hard: the jax.distributed shutdown barrier can
        # never complete with the victim gone (the coordination service
        # would FATAL this process ~90s later at interpreter teardown) —
        # like the reference's survivors after mpirun kills a world.
        print(f"WORKER-OK {os.environ['HOROVOD_RANK']}", flush=True)
        os._exit(0)

    elif scenario == "local_crash":
        # A rank whose ENGINE dies from a local fault while its process
        # stays alive must still be treated as a rank death: its crash-path
        # close carries no clean-detach, so the controller aborts the peers
        # instead of leaving them parked in the cycle rendezvous forever.
        import time

        from horovod_tpu.ops.engine import get_engine

        victim = size - 1
        hvd.allreduce(np.ones((4,), np.float32), average=False,
                      name="lc.barrier")
        if rank == victim:
            engine = get_engine()

            def _boom(entry):
                raise RuntimeError("injected local engine fault")

            engine._request_of = _boom
            h = hvd.allreduce_async(np.ones((8,), np.float32),
                                    name="lc.trigger")
            try:
                hvd.synchronize(h)
            except hvd.HorovodInternalError:
                pass  # own handle flushed by the dying loop
            time.sleep(5.0)  # stay alive: only the engine is dead
            return  # skip the hvd.shutdown() handshake below via early exit
        handles = [hvd.allreduce_async(np.full((64,), float(rank),
                                               np.float32),
                                       average=False, name=f"lc.{i}")
                   for i in range(4)]
        t0 = time.monotonic()
        try:
            for h in handles:
                hvd.synchronize(h)
        except hvd.HorovodInternalError as exc:
            assert "shut down" in str(exc), exc
        else:
            raise AssertionError("expected SHUT_DOWN_ERROR after engine "
                                 "death on a peer")
        assert time.monotonic() - t0 < 30.0

    elif scenario == "object":
        obj = {"root": "payload", "rank": 0} if rank == 0 else None
        out = hvd.broadcast_object(obj, root_rank=0)
        assert out == {"root": "payload", "rank": 0}

    else:
        raise ValueError(f"unknown scenario {scenario}")

    hvd.shutdown()


def _subset_scenario(scenario: str) -> None:
    """Subset worlds (``hvd.init(ranks=[...])``): members form a communicator
    in list order; non-members get a self-world; launcher world-rank 0
    hosts the controller service even as a non-member
    (reference ``operations.cc:1728-1742`` / ``common/__init__.py:58-84``).

    subset_02: 3-process world, ranks=[0, 2]  (member coordinator host)
    subset_12: 3-process world, ranks=[1, 2]  (NON-member coordinator host)
    """
    world_rank = int(os.environ["HOROVOD_RANK"])
    subset = {"subset_02": [0, 2], "subset_12": [1, 2]}[scenario]
    hvd.init(ranks=subset)
    if world_rank in subset:
        my = subset.index(world_rank)
        assert hvd.rank() == my, (hvd.rank(), my)
        assert hvd.size() == len(subset)
        # members allreduce their WORLD rank: the sum proves exactly the
        # subset participated
        out = hvd.allreduce(np.full((4,), float(world_rank), np.float32),
                            average=False, name="sub.sum")
        np.testing.assert_array_equal(np.asarray(out), float(sum(subset)))
        # broadcast from the last subset member
        root = len(subset) - 1
        b = hvd.broadcast(np.full((2,), float(world_rank), np.float32),
                          root_rank=root, name="sub.bcast")
        np.testing.assert_array_equal(np.asarray(b), float(subset[-1]))
    else:
        # non-member: self-world; collectives act locally and cannot hang
        assert hvd.rank() == 0 and hvd.size() == 1
        out = hvd.allreduce(np.full((4,), 7.0, np.float32),
                            average=False, name="sub.self")
        np.testing.assert_array_equal(np.asarray(out), 7.0)
        if world_rank == 0:
            # service host: stay alive while the members finish (shutdown's
            # grace period would cover this, but do not rely on timing)
            import time

            time.sleep(3.0)
    hvd.shutdown()


if __name__ == "__main__":
    main()
    print(f"WORKER-OK {os.environ['HOROVOD_RANK']}", flush=True)
    if _coord:
        # _exit skips atexit, so leave the multi-process JAX world
        # gracefully first — an abrupt drop of the rank-0 coordination
        # service errors peers still inside their own teardown barrier.
        jax.distributed.shutdown()
    # Skip interpreter teardown: with torch AND jax loaded in one process,
    # C++ static-destructor ordering at exit can abort (SIGABRT) under
    # heavy scheduling pressure — observed once on the loaded single-core
    # CI box (torch_grad rank died -6 AFTER all assertions and
    # hvd.shutdown() completed). Everything the scenarios verify has
    # already run; _exit only skips the hazardous library unwind.
    os._exit(0)
