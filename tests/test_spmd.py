"""SPMD collectives over a real 8-device mesh — the TPU hot path.

These are the "true collectives" of the suite (reference runs real MPI even
single-process, SURVEY §4): XLA executes real all-reduce/all-gather on the
virtual CPU mesh, identical lowering to the ICI collectives on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def _mesh():
    return data_parallel_mesh()


def test_mesh_shape():
    mesh = _mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == (DATA_AXIS,)


def test_spmd_allreduce_sum_and_mean(hvd):
    mesh = _mesh()
    x = jnp.arange(8.0, dtype=jnp.float32)  # shard i holds value i

    def step(xs):
        s = hvd.allreduce(xs, average=False, axis_name=DATA_AXIS)
        m = hvd.allreduce(xs, average=True, axis_name=DATA_AXIS)
        return s, m

    s, m = jax.jit(shard_map(step, mesh=mesh, in_specs=P(DATA_AXIS),
                             out_specs=(P(), P())))(x)
    np.testing.assert_allclose(np.asarray(s), 28.0)
    np.testing.assert_allclose(np.asarray(m), 3.5)


def test_spmd_allgather(hvd):
    mesh = _mesh()
    x = jnp.arange(16.0, dtype=jnp.float32).reshape(8, 2)

    def gather(xs):
        # each shard returns its full gathered copy; stacking them under
        # P(data) lets us check every shard saw the identical concat
        return hvd.allgather(xs, axis_name=DATA_AXIS)[None]

    out = jax.jit(shard_map(gather, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P(DATA_AXIS)))(x)
    assert out.shape == (8, 8, 2)
    for shard in np.asarray(out):
        np.testing.assert_array_equal(shard, np.asarray(x))


def test_spmd_broadcast(hvd):
    mesh = _mesh()
    x = jnp.arange(8.0, dtype=jnp.float32)

    def bcast(xs):
        return hvd.broadcast(xs, root_rank=3, axis_name=DATA_AXIS)

    out = jax.jit(shard_map(bcast, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P(DATA_AXIS)))(x)
    np.testing.assert_array_equal(np.asarray(out), np.full(8, 3.0))


def test_spmd_reducescatter(hvd):
    from horovod_tpu.ops import spmd

    mesh = _mesh()
    x = jnp.ones((64, 8), dtype=jnp.float32)  # (8, 8) per shard

    def rs(xs):
        return spmd.reducescatter(xs, DATA_AXIS)

    out = jax.jit(shard_map(rs, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P(DATA_AXIS)))(x)
    # every shard contributed an (8, 8) block of ones; the summed block (all
    # 8s) is scattered one row per shard, reassembling to (8, 8) of 8s
    np.testing.assert_array_equal(np.asarray(out), np.full((8, 8), 8.0))


def test_hierarchical_mesh_axes(hvd):
    mesh = hvd.parallel.hierarchical_mesh()
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (1, 8)

    x = jnp.arange(8.0, dtype=jnp.float32)

    def two_level(xs):
        # psum along ici then dcn == global psum (operations.cc:1284-1436
        # hierarchical allreduce, factored per axis)
        return jax.lax.psum(jax.lax.psum(xs, "ici"), "dcn")

    out = jax.jit(shard_map(two_level, mesh=mesh, in_specs=P(("dcn", "ici")),
                            out_specs=P()))(x)
    np.testing.assert_allclose(np.asarray(out), 28.0)


def test_eager_spmd_equivalence(hvd):
    """The eager engine and the SPMD path must agree on semantics."""
    mesh = _mesh()
    x = jnp.full((8, 4), 2.0, dtype=jnp.float32)

    def mean(xs):
        return hvd.allreduce(xs, average=True, axis_name=DATA_AXIS)

    spmd_out = jax.jit(shard_map(mean, mesh=mesh, in_specs=P(DATA_AXIS),
                                 out_specs=P()))(x)
    eager_out = hvd.allreduce(np.full((4,), 2.0, np.float32), average=True)
    np.testing.assert_allclose(np.asarray(spmd_out)[0], np.asarray(eager_out))


def test_dp_step_compiles_to_one_fused_allreduce(hvd):
    """Perf hygiene on the multi-chip product path: the compiled DP train
    step must carry its ~100 per-leaf gradient psums + BN pmeans as a
    handful of fused all-reduces spanning the whole mesh (XLA's
    AllReduceCombiner is the compiled-away fusion buffer), and must not
    reshard replicated params (no all-to-all / collective-permute /
    all-gather / reduce-scatter). A regression here — e.g. an optimizer
    change that breaks combining, or a spec change that secretly shards
    params — multiplies per-step collective launches or moves param-sized
    traffic every step, the two failure modes that silently destroy
    scaling efficiency."""
    import re

    import optax
    from jax.sharding import Mesh

    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import ResNet
    from horovod_tpu.models.resnet import BottleneckResNetBlock

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dcn", "ici"))
    model = ResNet(stage_sizes=[1, 1], num_filters=8, num_classes=10,
                   block_cls=BottleneckResNetBlock, dtype=jnp.float32)
    x = jnp.ones((16, 16, 16, 3), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = hvd.DistributedOptimizer(optax.sgd(0.01),
                                   axis_name=("dcn", "ici"))
    opt_state = opt.init(params)
    step = make_dp_train_step(model, opt, mesh, axis_name=("dcn", "ici"))
    hlo = step.lower(params, opt_state, batch_stats, x, y).compile().as_text()

    n_ar = len(re.findall(r"all-reduce\(|all-reduce-start", hlo))
    if n_ar > 4:
        # Combiner probe: two adjacent tiny psums in a trivial program.
        # If even THOSE stay separate, this XLA build simply does not run
        # the AllReduceCombiner pass on this backend (observed on the
        # CPU pipeline of the jax 0.4.37 image) — the repo cannot have
        # broken a pass the compiler never runs, so gate loudly instead
        # of failing on the environment. A real combining backend that
        # merges the probe but leaves the DP step's 47 psums unfused
        # still fails below, which is the regression this test exists
        # to catch.
        probe = jax.jit(shard_map(
            lambda a, b: (jax.lax.psum(a, ("dcn", "ici")),
                          jax.lax.psum(b, ("dcn", "ici"))),
            mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        )).lower(jnp.ones(8), jnp.ones(8)).compile().as_text()
        n_probe = len(re.findall(r"all-reduce\(|all-reduce-start", probe))
        if n_probe > 1:
            pytest.skip(
                "XLA's AllReduceCombiner does not run on this backend "
                f"(a trivial 2-psum program compiles to {n_probe} "
                "all-reduces); the compiled-away fusion buffer cannot be "
                "asserted here")
    assert 1 <= n_ar <= 4, f"{n_ar} all-reduce ops (combiner broken?)"
    groups = set(re.findall(r"replica_groups=(\{\{[^}]*\}\})", hlo))
    assert groups == {"{{0,1,2,3,4,5,6,7}}"}, groups  # whole-mesh groups
    # bare substrings so the async -start/-done spellings match too
    for op in ("all-to-all", "collective-permute", "all-gather",
               "reduce-scatter"):
        assert op not in hlo, f"unexpected {op} in the DP step"


def test_hierarchical_dp_step_two_level_collectives():
    """The hierarchical twin of the fused-allreduce shape test, at 16
    virtual devices on a (4 dcn, 4 ici) mesh with
    HOROVOD_HIERARCHICAL_ALLREDUCE=1 (round-3 verdict, next-round #5):
    gradient traffic must compile to the factored two-level pattern of
    ``parallel/hierarchical.py`` — reduce-scatter over the ici axis,
    all-reduce of the 1/|ici| shard over the dcn axis, all-gather back
    over ici (``operations.cc:1284-1436``'s bandwidth shape) — not a flat
    whole-mesh all-reduce per gradient. Subprocess: needs its own
    device-count global (16 > the suite's 8)."""
    import subprocess
    import sys
    import os

    prog = r"""
import os, re
os.environ.pop("JAX_PLATFORMS", None)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp, optax
from jax.sharding import Mesh
import horovod_tpu as hvd
from benchmarks._dp_step import make_dp_train_step
from horovod_tpu.models import ResNet
from horovod_tpu.models.resnet import BottleneckResNetBlock

hvd.init()
devices = jax.devices()[:16]
mesh = Mesh(np.asarray(devices).reshape(4, 4), ("dcn", "ici"))
model = ResNet(stage_sizes=[1, 1], num_filters=8, num_classes=10,
               block_cls=BottleneckResNetBlock, dtype=jnp.float32)
x = jnp.ones((32, 16, 16, 3), jnp.float32)
y = jnp.zeros((32,), jnp.int32)
variables = model.init(jax.random.PRNGKey(0), x)
params, batch_stats = variables["params"], variables["batch_stats"]
opt = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name=("dcn", "ici"))
opt_state = opt.init(params)
step = make_dp_train_step(model, opt, mesh, axis_name=("dcn", "ici"))
hlo = step.lower(params, opt_state, batch_stats, x, y).compile().as_text()

# device id = 4*dcn + ici, so ici groups are contiguous quads and dcn
# groups are stride-4 quads
ICI = "{{0,1,2,3},{4,5,6,7},{8,9,10,11},{12,13,14,15}}"
DCN = "{{0,4,8,12},{1,5,9,13},{2,6,10,14},{3,7,11,15}}"

def groups_of(op):
    pat = op + r"[^\n]*replica_groups=(\{\{[0-9,{}]*\}\})"
    return set(re.findall(pat, hlo))

rs, ag, ar = (groups_of("reduce-scatter"), groups_of("all-gather"),
              groups_of("all-reduce"))
assert ICI in rs, ("reduce-scatter not over ici", rs)
assert ICI in ag, ("all-gather not over ici", ag)
assert DCN in ar, ("no dcn-axis all-reduce of the reduced shard", ar)
# gradient bytes must NOT ride a flat whole-mesh all-reduce; the only
# legitimate whole-mesh reduces are the BN-stat/loss pmeans the step
# does outside the optimizer, so whole-mesh groups may appear — but the
# factored legs above prove the gradient path took the hierarchy.
step_flat = make_dp_train_step(
    model, hvd.DistributedOptimizer(optax.sgd(0.01),
                                    axis_name=("dcn", "ici"),
                                    hierarchical=False),
    mesh, axis_name=("dcn", "ici"), hierarchical=False)
hlo_flat = step_flat.lower(params, opt_state, batch_stats, x,
                           y).compile().as_text()
assert "reduce-scatter" not in hlo_flat, "flat path grew a reduce-scatter?"
hvd.shutdown()
print("HIER-OK")
"""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run([sys.executable, "-c", prog], cwd=root, env=env,
                            capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, result.stderr
    assert "HIER-OK" in result.stdout


def test_hierarchical_step_matches_flat_numerically(hvd):
    """The factored reduce_scatter/psum/all_gather route must be a pure
    implementation detail: one hierarchical train step from a shared init
    produces the same parameters as the flat whole-mesh psum step."""
    import optax
    from jax.sharding import Mesh

    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import ResNet
    from horovod_tpu.models.resnet import BottleneckResNetBlock

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dcn", "ici"))
    model = ResNet(stage_sizes=[1, 1], num_filters=8, num_classes=10,
                   block_cls=BottleneckResNetBlock, dtype=jnp.float32)
    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (16, 16, 16, 3), jnp.float32)
    y = jnp.arange(16, dtype=jnp.int32) % 10
    variables = model.init(jax.random.PRNGKey(0), x)

    outs = {}
    for hier in (False, True):
        params = variables["params"]
        batch_stats = variables["batch_stats"]
        opt = hvd.DistributedOptimizer(optax.sgd(0.01),
                                       axis_name=("dcn", "ici"),
                                       hierarchical=hier)
        opt_state = opt.init(params)
        step = make_dp_train_step(model, opt, mesh,
                                  axis_name=("dcn", "ici"),
                                  donate=False, hierarchical=hier)
        outs[hier] = step(params, opt_state, batch_stats, x, y)

    flat_p, _, flat_bn = outs[False]
    hier_p, _, hier_bn = outs[True]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        flat_p, hier_p)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        flat_bn, hier_bn)


def test_compressed_dp_step_reduces_in_bf16(hvd):
    """--fp16-allreduce must COMPRESS THE WIRE: with explicit_grad_reduce
    the compiled gradient all-reduce carries bf16 operands (under vma
    tracking the auto-psum would run f32 before the compress hook, making
    the flag numerics-only). Parameters stay close to the uncompressed
    step."""
    import optax

    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import ResNet
    from horovod_tpu.models.resnet import ResNetBlock

    mesh = _mesh()
    model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10,
                   block_cls=ResNetBlock, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 16, 16, 3),
                          jnp.float32)
    y = jnp.arange(16, dtype=jnp.int32) % 10
    variables = model.init(jax.random.PRNGKey(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def bf16_all_reduces(step, opt_state):
        # assert on the LOWERED program (what the step requests): backend
        # passes may promote bf16 reduces to f32 on CPU (no native bf16),
        # but TPU executes them natively — the request is the contract
        txt = step.lower(params, opt_state, batch_stats, x, y).as_text()
        ars = txt.split('"stablehlo.all_reduce"')[1:]
        return (len(ars),
                sum(1 for a in ars if "-> tensor<" in a
                    and "bf16>" in a.split("->", 1)[1][:60]))

    opt_c = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name=DATA_AXIS,
                                     compression=hvd.Compression.bf16)
    step_c = make_dp_train_step(model, opt_c, mesh, axis_name=DATA_AXIS,
                                donate=False, explicit_grad_reduce=True)
    total, bf16_n = bf16_all_reduces(step_c, opt_c.init(params))
    # a format change that breaks the scan must fail loudly, not pass 0>=0
    assert total > 0, "no stablehlo.all_reduce found in the lowered text"
    # every gradient leaf reduces in bf16; only BN-stat pmeans + the loss
    # legitimately stay f32
    assert bf16_n >= total // 2, (
        f"only {bf16_n}/{total} all_reduces are bf16 — compression is "
        f"not on the wire")

    opt_p = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name=DATA_AXIS)
    step_p = make_dp_train_step(model, opt_p, mesh, axis_name=DATA_AXIS,
                                donate=False)
    pc, _, _ = step_c(params, opt_c.init(params), batch_stats, x, y)
    pp, _, _ = step_p(params, opt_p.init(params), batch_stats, x, y)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-3), pc, pp)
