"""Flax front-end (Keras-front-end parity; reference
``horovod/_keras/__init__.py`` + ``test/test_keras.py:62-246``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg
import horovod_tpu.flax as hvd_flax
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def _apply_fn(variables, x):
    return x @ variables["params"]["w"]


def _make_state(axis_name=None, **kw):
    params = {"w": jnp.ones((4, 2))}
    return hvd_flax.DistributedTrainState.create(
        apply_fn=_apply_fn, params=params, tx=optax.sgd(0.5),
        axis_name=axis_name, **kw)


def test_apply_gradients_eager_matches_sgd(hvd):
    """Size-1 world: wrapped TrainState must match plain optax sgd."""
    state = _make_state()
    grads = {"w": jnp.full((4, 2), 2.0)}
    new_state = state.apply_gradients(grads=grads)
    np.testing.assert_allclose(np.asarray(new_state.params["w"]),
                               np.ones((4, 2)) - 0.5 * 2.0)
    assert int(new_state.step) == 1


def test_apply_gradients_spmd_averages(hvd):
    """Per-shard grads differ; params must move by the mean gradient."""
    mesh = data_parallel_mesh()
    state = _make_state(axis_name=DATA_AXIS)
    gs = jnp.arange(8.0, dtype=jnp.float32).reshape(8, 1, 1)  # shard i -> i

    def step(state, g):
        grads = {"w": jnp.broadcast_to(g[0], (4, 2))}
        return state.apply_gradients(grads=grads)

    new_state = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(), P(DATA_AXIS)),
        out_specs=P()))(state, gs)
    # mean(0..7) = 3.5, lr 0.5 -> params = 1 - 1.75
    np.testing.assert_allclose(np.asarray(new_state.params["w"]),
                               np.full((4, 2), 1.0 - 0.5 * 3.5))


def test_backward_passes_per_step(hvd):
    """Delay-counter accumulation inside a TrainState
    (``torch/__init__.py:71-73,114-130`` semantics)."""
    state = _make_state(backward_passes_per_step=2)
    g = {"w": jnp.ones((4, 2))}
    s1 = state.apply_gradients(grads=g)
    np.testing.assert_allclose(np.asarray(s1.params["w"]), 1.0)  # accumulating
    s2 = s1.apply_gradients(grads=g)
    np.testing.assert_allclose(np.asarray(s2.params["w"]),
                               1.0 - 0.5 * 2.0)  # sum of 2 passes


def test_save_load_model_roundtrip(hvd, tmp_path):
    """``hvd.load_model`` round-trip (``test/test_keras.py:62-246``): the
    restored state keeps the distributed optimizer wrap (via the template)
    and identical leaves, and training can continue."""
    state = _make_state()
    state = state.apply_gradients(grads={"w": jnp.full((4, 2), 2.0)})
    path = str(tmp_path / "ckpt")
    hvd_flax.save_model(path, state)

    template = _make_state()
    restored = hvd_flax.load_model(path, template)
    np.testing.assert_allclose(np.asarray(restored.params["w"]),
                               np.asarray(state.params["w"]))
    assert int(restored.step) == int(state.step) == 1
    # Optimizer wrap survived: another step still averages (size-1 no-op,
    # but the DistributedOptState structure proves the wrap is in place).
    again = restored.apply_gradients(grads={"w": jnp.ones((4, 2))})
    assert int(again.step) == 2
    assert type(again.opt_state).__name__ == "DistributedOptState"


def test_broadcast_train_state(hvd):
    """Rank-0 push leaves a size-1 state unchanged but exercises the full
    named-broadcast path over every leaf."""
    state = _make_state()
    out = hvd_flax.broadcast_train_state(state, root_rank=0)
    np.testing.assert_allclose(np.asarray(out.params["w"]),
                               np.asarray(state.params["w"]))
    assert out.apply_fn is state.apply_fn


def test_create_distributed_optimizer_alias(hvd):
    """Keras-parity entry point returns a working GradientTransformation."""
    tx = hvd_flax.create_distributed_optimizer(optax.sgd(0.1))
    params = {"w": jnp.ones(3)}
    s = tx.init(params)
    u, _ = tx.update({"w": jnp.ones(3)}, s, params)
    np.testing.assert_allclose(np.asarray(u["w"]), -0.1)


def test_no_double_wrap(hvd):
    """A pre-wrapped optimizer passed to create() must not be wrapped again
    (double allreduce / double compression / N*N delay counters)."""
    tx = hvd_flax.create_distributed_optimizer(optax.sgd(0.5))
    params = {"w": jnp.ones((4, 2))}
    state = hvd_flax.DistributedTrainState.create(
        apply_fn=_apply_fn, params=params, tx=tx)
    # Single wrap: opt_state is one DistributedOptState whose inner is the
    # raw sgd state, not another DistributedOptState.
    assert type(state.opt_state).__name__ == "DistributedOptState"
    assert type(state.opt_state.inner).__name__ != "DistributedOptState"
    new_state = state.apply_gradients(grads={"w": jnp.full((4, 2), 2.0)})
    np.testing.assert_allclose(np.asarray(new_state.params["w"]),
                               np.ones((4, 2)) - 0.5 * 2.0)


def test_package_export():
    assert hvd_pkg.flax is hvd_flax
