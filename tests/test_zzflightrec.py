"""Flight recorder + black-box incident dumps (docs/blackbox.md).

Named past the 870 s tier-1 truncation point on purpose (the ROADMAP
note): the unit tier is cheap, but the dump-on-abort worlds each spawn
2-process runs.

Coverage per the ISSUE-14 satellite: ring overwrite / capacity /
thread-safety units, dump-on-abort under ``nan@rank1`` and ``drop/
close@rank1`` chaos cells asserting the classifier names the INJECTED
rank on both negotiation cores, native-controller rank-local degrade,
the disabled-knob zero-overhead path, the ``tools/blackbox_report.py``
final-line-JSON contract, and the 2-proc ``dryrun_flightrec``
certification (slow tier).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading

import pytest

from horovod_tpu.core.config import (
    HOROVOD_CHAOS,
    HOROVOD_FLIGHTREC,
    HOROVOD_FLIGHTREC_DIR,
    HOROVOD_FLIGHTREC_DUMP_TIMEOUT,
    HOROVOD_FLIGHTREC_LAUNCH_GRACE,
    HOROVOD_GRAD_SENTRY,
    HOROVOD_NATIVE_CONTROLLER,
    HOROVOD_NATIVE_CORE,
    HOROVOD_RECONNECT_ATTEMPTS,
    HOROVOD_RECONNECT_BACKOFF,
    HOROVOD_RECONNECT_WINDOW,
    HOROVOD_STALL_SHUTDOWN_TIME,
    HOROVOD_STALL_WARNING_TIME,
)
from horovod_tpu.obs import flightrec

pytestmark = pytest.mark.flightrec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fresh_recorder(monkeypatch):
    """A clean enabled recorder rebuilt from env, restored afterwards."""
    monkeypatch.delenv(HOROVOD_FLIGHTREC, raising=False)
    monkeypatch.delenv("HOROVOD_FLIGHTREC_EVENTS", raising=False)
    flightrec.reset_for_tests()
    yield flightrec.recorder()
    flightrec.reset_for_tests()


# -- ring units ----------------------------------------------------------------


class TestRing:
    def test_capacity_and_overwrite(self):
        rec = flightrec.FlightRecorder(capacity=4, enabled=True)
        for i in range(7):
            rec.record("negotiate", i)
        assert rec.recorded == 7
        assert rec.dropped == 3
        tail = rec.tail()
        assert [e[2] for e in tail] == [3, 4, 5, 6]  # oldest overwritten
        assert all(e[1] == "negotiate" for e in tail)

    def test_tail_under_capacity(self):
        rec = flightrec.FlightRecorder(capacity=8, enabled=True)
        rec.record("enqueue", detail="t0")
        rec.record("response", 5, aux=2)
        tail = rec.tail()
        assert len(tail) == 2
        assert tail[0][1] == "enqueue" and tail[0][4] == "t0"
        assert tail[1][:4] == [tail[1][0], "response", 5, 2]
        assert tail[1][0] > 0  # monotonic timestamp stamped

    def test_tail_returns_copies(self):
        rec = flightrec.FlightRecorder(capacity=4, enabled=True)
        rec.record("negotiate", 1)
        tail = rec.tail()
        tail[0][1] = "mutated"
        assert rec.tail()[0][1] == "negotiate"

    def test_thread_safety(self):
        rec = flightrec.FlightRecorder(capacity=128, enabled=True)
        n_threads, per_thread = 8, 500

        def worker(tid):
            for i in range(per_thread):
                rec.record("negotiate", i, aux=tid)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert rec.recorded == n_threads * per_thread
        tail = rec.tail()
        assert len(tail) == 128
        # every slot is a complete, well-formed record (no torn writes)
        for event in tail:
            assert event[1] == "negotiate"
            assert 0 <= event[2] < per_thread
            assert 0 <= event[3] < n_threads

    def test_disabled_records_nothing(self):
        rec = flightrec.FlightRecorder(capacity=16, enabled=False)
        rec.record("negotiate", 1)
        assert rec.recorded == 0
        assert rec.tail() == []
        assert rec.stats()["enabled"] is False

    def test_disabled_knob_zero_overhead(self, monkeypatch):
        """HOROVOD_FLIGHTREC=0: the module-level producer is one global
        read + one attribute check — zero allocation per call (the
        registry-measured no-added-allocation acceptance)."""
        import tracemalloc

        monkeypatch.setenv(HOROVOD_FLIGHTREC, "0")
        flightrec.reset_for_tests()
        try:
            assert flightrec.recorder().enabled is False
            flightrec.record("negotiate", 1)  # warm the singleton path
            tracemalloc.start()
            before = tracemalloc.take_snapshot()
            for i in range(2000):
                flightrec.record("negotiate", i, aux=3, detail="grad")
            after = tracemalloc.take_snapshot()
            tracemalloc.stop()
            stats = after.compare_to(before, "filename")
            grown = sum(s.size_diff for s in stats if s.size_diff > 0)
            # tracemalloc bookkeeping itself can show a few hundred
            # bytes; 2000 recorded events would show tens of KB
            assert grown < 4096, f"disabled record() allocated {grown}B"
            assert flightrec.recorder().recorded == 0
        finally:
            flightrec.reset_for_tests()

    def test_env_capacity_and_counters(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHTREC_EVENTS", "32")
        monkeypatch.delenv(HOROVOD_FLIGHTREC, raising=False)
        flightrec.reset_for_tests()
        try:
            rec = flightrec.recorder()
            assert rec.capacity == 32
            from horovod_tpu.obs.registry import registry

            for i in range(40):
                flightrec.record("negotiate", i)
            snap = registry().snapshot()
            assert flightrec.FAMILY_EVENTS in snap
            assert flightrec.FAMILY_DROPPED in snap
            assert flightrec.FAMILY_DUMPS in snap
            assert flightrec.FAMILY_DUMP_FAILURES in snap
        finally:
            flightrec.reset_for_tests()


# -- classifier units ----------------------------------------------------------


def _events(*triples):
    """[(kind, ordinal, detail?), ...] -> event records."""
    out = []
    for i, spec in enumerate(triples):
        kind, ordinal = spec[0], spec[1]
        detail = spec[2] if len(spec) > 2 else ""
        out.append([1000 + i, kind, ordinal, -1, detail])
    return out


class TestClassifier:
    def test_dead_rank_with_agreed_cycle(self):
        doc = {
            "world_id": "full:2", "epoch": 0,
            "reason": "rank 1 exited mid-job. shut down "
                      "[aborted ranks: 1]",
            "ranks": {
                "0": {"events": _events(("negotiate", 0), ("response", 0),
                                        ("negotiate", 1), ("response", 1),
                                        ("negotiate", 2))},
                "1": {"events": _events(("negotiate", 0), ("response", 0),
                                        ("negotiate", 1))},
            },
        }
        report = flightrec.classify_incident(doc)
        assert report["verdict"] == "dead@rank1 cycle 1"
        assert report["last_agreed_cycle"] == 1
        assert report["first_diverging_rank"] == 1
        assert report["fork_event"][1] == "negotiate"

    def test_stall_verdict(self):
        from horovod_tpu.core.status import format_aborted_ranks

        doc = {
            "reason": "collective(s) grad stalled past the 4s "
                      "HOROVOD_STALL_SHUTDOWN_TIME_S deadline; aborting "
                      f"the world. {format_aborted_ranks([2])}",
            "ranks": {"0": {"events": _events(("response", 417))},
                      "2": {"events": _events(("response", 417))}},
        }
        report = flightrec.classify_incident(doc)
        assert report["verdict"] == "stall@rank2 cycle 417"

    def test_consensus_verdict_with_window(self):
        from horovod_tpu.core.status import format_consensus

        doc = {
            "reason": "cross-rank consensus verification failed "
                      f"{format_consensus([1], ['grad'])} shut down",
            "ranks": {
                "0": {"events": _events(("consensus_seal", 12))},
                "1": {"events": _events(("consensus_seal", 12))},
            },
        }
        report = flightrec.classify_incident(doc)
        assert report["verdict"] == "consensus-fork@rank1 window 12"

    def test_nonfinite_prefers_chaos_evidence(self):
        from horovod_tpu.core.status import format_nonfinite

        # the NaN propagates through the sum: BOTH ranks' sentry kinds
        # read nan — only the injection event names the culprit
        doc = {
            "reason": f"grad sentry abort {format_nonfinite(3, ['g'])}",
            "ranks": {
                "0": {"events": _events(("sentry", 3, "abort:nan"))},
                "1": {"events": _events(("chaos", 3, "nan"),
                                        ("sentry", 3, "abort:nan"))},
            },
        }
        report = flightrec.classify_incident(doc)
        assert report["verdict"] == "nonfinite@rank1 step 3"
        assert report["chaos_ranks"] == [1]

    def test_nonfinite_ignores_wire_chaos_on_other_rank(self):
        """A co-occurring WIRE fault (delay/drop/close) on a lower rank
        is harmless to the numerics and must not steal the non-finite
        attribution from the rank that recorded the DATA injection."""
        from horovod_tpu.core.status import format_nonfinite

        doc = {
            "reason": f"grad sentry abort {format_nonfinite(3, ['g'])}",
            "ranks": {
                "0": {"events": _events(("chaos", 2, "delay"),
                                        ("sentry", 3, "abort:nan"))},
                "1": {"events": _events(("chaos", 3, "nan"),
                                        ("sentry", 3, "abort:nan"))},
            },
        }
        report = flightrec.classify_incident(doc)
        assert report["verdict"] == "nonfinite@rank1 step 3"
        # chaos_ranks still reports every injected stream — only the
        # culprit selection filters to data-plane kinds
        assert report["chaos_ranks"] == [0, 1]

    def test_data_chaos_kinds_pinned_to_chaos_contract(self):
        """The classifier's kind list is a deliberate copy of
        chaos.DATA_KINDS (flightrec.py must stay loadable without the
        package) — pin them together like the wire-tag regexes."""
        from horovod_tpu import chaos

        assert flightrec.DATA_CHAOS_KINDS == chaos.DATA_KINDS

    def test_desync_verdict(self):
        doc = {"reason": "negotiation cycle stream desync: rank 0 at "
                         "cycle 4, rank 1 at cycle 5 joined one "
                         "rendezvous",
               "ranks": {}}
        assert flightrec.classify_incident(doc)["verdict"] == \
            "desync: flush_ordinal"

    def test_specific_tag_found_in_rank_error(self):
        """The coordinator's reason can be the generic rank death while
        the structured tag only survives in a rank's error field."""
        from horovod_tpu.core.status import format_consensus

        doc = {
            "reason": "rank 1 exited mid-job. [aborted ranks: 1]",
            "ranks": {
                "0": {"events": [],
                      "error": f"boom {format_consensus([1], [])}"},
            },
        }
        assert flightrec.classify_incident(doc)["verdict"].startswith(
            "consensus-fork@rank1")

    def test_tag_regexes_pinned_to_status_contract(self):
        """The classifier's regex copies must keep matching what
        core/status.py actually formats (the deliberate-duplication
        cross-pin: flightrec.py must stay loadable without the
        package)."""
        from horovod_tpu.core.status import (
            format_aborted_ranks,
            format_consensus,
            format_nonfinite,
        )

        assert flightrec._ABORTED_RE.search(format_aborted_ranks([3, 1]))
        assert flightrec._CONSENSUS_RE.search(
            format_consensus([2], ["t"]))
        assert flightrec._NONFINITE_RE.search(format_nonfinite(7, ["t"]))

    def test_merge_incidents_unions_ranks(self):
        merged = flightrec.merge_incidents([
            {"world_id": "full:2", "epoch": 0, "reason": "",
             "ranks": {"1": {"events": [], "error": "e1"}},
             "written_by": "rank-local:1"},
            {"world_id": "full:2", "epoch": 0, "reason": "r",
             "ranks": {"0": {"events": []}},
             "coordinator": {"snapshot": {}},
             "written_by": "coordinator"},
        ])
        assert sorted(merged["ranks"]) == ["0", "1"]
        assert merged["reason"] == "r"
        assert merged["coordinator"] is not None

    def test_incident_filename_sanitized(self):
        assert flightrec.incident_filename("full:2", 0) == \
            "blackbox-full-2-0.json"
        assert flightrec.incident_filename("sub:0,1", 3, rank=1) == \
            "blackbox-sub-0-1-3.rank1.json"


# -- dump plumbing units -------------------------------------------------------


class TestDumpPlumbing:
    def test_unarmed_trigger_is_noop(self, tmp_path, monkeypatch,
                                     fresh_recorder):
        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        flightrec.disarm_push()
        assert flightrec.trigger_dump("synthetic [aborted ranks: 1]") \
            is None
        assert list(tmp_path.iterdir()) == []

    def test_structured_raise_unarmed_writes_nothing(
            self, tmp_path, monkeypatch, fresh_recorder):
        from horovod_tpu.core.status import Status

        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        flightrec.disarm_push()
        with pytest.raises(Exception):
            Status.unknown_error("x [aborted ranks: 1]").raise_if_error()
        assert list(tmp_path.iterdir()) == []

    def test_local_degrade_writes_rank_file_once(self, tmp_path,
                                                 monkeypatch,
                                                 fresh_recorder):
        """The native-controller degrade: local_only=True writes one
        rank-local incident file; the once-flag makes a second trigger
        (the raise_if_error hook racing the loop teardown) a no-op."""
        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        flightrec.record("negotiate", 0)
        flightrec.record("response", 0)
        flightrec.arm_push(None, None, "full:2", 1, 0,
                           snapshot_fn=lambda: {"x": 1}, local_only=True)
        try:
            path = flightrec.trigger_dump(
                "rank 0 exited mid-job. [aborted ranks: 0]")
            assert path is not None and os.path.exists(path)
            assert path.endswith(".rank1.json")
            assert flightrec.trigger_dump("again") is None  # once
            with open(path) as fh:
                doc = json.load(fh)
            assert doc["written_by"] == "rank-local:1"
            assert doc["ranks"]["1"]["snapshot"] == {"x": 1}
            assert any(e[1] == "abort"
                       for e in doc["ranks"]["1"]["events"])
            report = flightrec.classify_incident(doc)
            assert report["verdict"].startswith("dead@rank0")
        finally:
            flightrec.disarm_push()

    def test_rearm_resets_once_flag(self, tmp_path, monkeypatch,
                                    fresh_recorder):
        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        flightrec.arm_push(None, None, "full:2", 0, 0, local_only=True)
        assert flightrec.trigger_dump("a [aborted ranks: 1]") is not None
        flightrec.arm_push(None, None, "full:2", 0, 1, local_only=True)
        try:
            assert flightrec.trigger_dump("b [aborted ranks: 1]") \
                is not None
        finally:
            flightrec.disarm_push()

    def test_disabled_recorder_never_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv(HOROVOD_FLIGHTREC, "0")
        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        flightrec.reset_for_tests()
        try:
            flightrec.arm_push(None, None, "full:2", 0, 0,
                               local_only=True)
            assert flightrec.trigger_dump("x [aborted ranks: 1]") is None
            assert list(tmp_path.iterdir()) == []
        finally:
            flightrec.reset_for_tests()

    def test_coordinator_collect_settles_on_partial_store(
            self, tmp_path, monkeypatch, fresh_recorder):
        """A dead rank never pushes: the collector must settle once
        pushes stop arriving instead of always eating the full
        timeout."""
        import time as _time

        monkeypatch.setenv(HOROVOD_FLIGHTREC_DIR, str(tmp_path))
        monkeypatch.setenv(HOROVOD_FLIGHTREC_DUMP_TIMEOUT, "30")
        store = {0: flightrec.rank_payload("r0 error", None)}
        t0 = _time.monotonic()
        thread = flightrec.coordinator_collect(
            "rank 1 exited mid-job. [aborted ranks: 1]", 2, "full:2", 0,
            store_get=lambda: dict(store),
            snapshot_fn=lambda: {"pending_rendezvous": {"cycle": {}}})
        thread.join(timeout=20)
        elapsed = _time.monotonic() - t0
        assert not thread.is_alive()
        assert elapsed < 10, f"collector waited {elapsed:.1f}s"
        files = list(tmp_path.glob("blackbox-*.json"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert doc["written_by"] == "coordinator"
        assert sorted(doc["ranks"]) == ["0"]
        assert doc["coordinator"]["snapshot"]["pending_rendezvous"] == \
            {"cycle": {}}


# -- blackbox_report.py tool contract ------------------------------------------


class TestBlackboxReportTool:
    def test_final_line_json_contract(self, tmp_path):
        doc = {
            "format": 1, "world_id": "full:2", "epoch": 0, "size": 2,
            "reason": "rank 1 exited mid-job. [aborted ranks: 1]",
            "written_by": "coordinator",
            "ranks": {
                "0": {"events": _events(("negotiate", 0), ("response", 0),
                                        ("negotiate", 1)),
                      "clock_offset_us": 12.5},
                "1": {"events": _events(("negotiate", 0),
                                        ("response", 0))},
            },
            "coordinator": {"snapshot": {
                "pending_rendezvous": {"cycle": {"('cycle', 1)": [0]}}}},
        }
        path = tmp_path / "blackbox-full-2-0.json"
        path.write_text(json.dumps(doc))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "blackbox_report.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["verdict"] == "dead@rank1 cycle 0"
        assert report["last_agreed_cycle"] == 0
        assert report["first_diverging_rank"] == 1
        assert report["sources"] == ["blackbox-full-2-0.json"]
        assert "parked cycle rendezvous" in proc.stdout

    def test_merges_rank_local_files(self, tmp_path):
        from horovod_tpu.core.status import format_nonfinite

        for rank in (0, 1):
            events = _events(("negotiate", 2), ("response", 2),
                             ("sentry", 3, "abort:nan"))
            if rank == 1:
                events = _events(("chaos", 3, "nan")) + events
            doc = {"world_id": "full:2", "epoch": 0,
                   "reason": f"x {format_nonfinite(3, ['g'])}",
                   "written_by": f"rank-local:{rank}",
                   "ranks": {str(rank): {"events": events}}}
            (tmp_path / f"blackbox-full-2-0.rank{rank}.json").write_text(
                json.dumps(doc))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "blackbox_report.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["verdict"] == "nonfinite@rank1 step 3"
        assert report["ranks_present"] == [0, 1]

    def test_no_files_is_an_error(self, tmp_path):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "blackbox_report.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1

    def test_flightrec_module_loads_without_the_package(self, tmp_path):
        """The jax-less exec-fallback contract: flightrec.py's module
        level must stay stdlib-only (the straggler_report precedent)."""
        script = (
            "import importlib.util, sys\n"
            "sys.modules['horovod_tpu'] = None  # poison package import\n"
            f"spec = importlib.util.spec_from_file_location('_fr', "
            f"{os.path.join(REPO, 'horovod_tpu', 'obs', 'flightrec.py')!r})\n"
            "mod = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(mod)\n"
            "doc = {'reason': 'rank 1 exited mid-job. "
            "[aborted ranks: 1]', 'ranks': {}}\n"
            "print(mod.classify_incident(doc)['verdict'])\n")
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == "dead@rank1 cycle ?"


# -- timeline dropped-events counter (satellite) -------------------------------


class TestTimelineDropCounter:
    def test_late_event_counts_on_registry(self, tmp_path):
        from horovod_tpu.obs.registry import registry
        from horovod_tpu.utils.timeline import (
            FAMILY_DROPPED_EVENTS,
            Timeline,
        )

        def total():
            fam = registry().snapshot().get(FAMILY_DROPPED_EVENTS)
            return fam["samples"][0]["value"] if fam else 0

        timeline = Timeline(str(tmp_path / "t.json"))
        timeline.meta("horovod_trace_meta", {"rank": 0})
        timeline.close()
        before = total()
        timeline.counter("late", {"x": 1})
        timeline.meta("late_meta", {"y": 2})
        assert total() == before + 2

    def test_disabled_timeline_drops_without_counting(self):
        from horovod_tpu.obs.registry import registry
        from horovod_tpu.utils.timeline import (
            FAMILY_DROPPED_EVENTS,
            Timeline,
        )

        def total():
            fam = registry().snapshot().get(FAMILY_DROPPED_EVENTS)
            return fam["samples"][0]["value"] if fam else 0

        timeline = Timeline("")  # disabled: no path
        timeline.close()
        before = total()
        timeline.counter("late", {"x": 1})
        assert total() == before  # no artifact to truncate


# -- health_report / introspect route (satellite) ------------------------------


class TestHealthReport:
    def test_shape_without_engine(self):
        import horovod_tpu as hvd

        report = hvd.health_report()
        assert set(report) >= {"initialized", "engine", "controller",
                               "flightrec"}
        assert report["flightrec"]["capacity"] >= 1

    def test_introspect_route_served(self):
        import urllib.request

        from horovod_tpu.obs import exposition, metrics_snapshot

        server = exposition.MetricsServer(
            0, lambda: {"world": metrics_snapshot(), "ranks": {}})
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/v1/introspect",
                    timeout=10) as resp:
                doc = json.loads(resp.read())
            assert "flightrec" in doc and "engine" in doc
        finally:
            server.close()

    def test_live_engine_snapshot(self, hvd):
        import numpy as np

        # the engine is lazy: one collective spins it up
        hvd.allreduce(np.ones(4, np.float32), name="flightrec.health")
        report = hvd.health_report()
        assert report["initialized"] is True
        engine = report["engine"]
        assert engine is not None
        assert engine["size"] == hvd.size()
        assert "inflight_flushes" in engine
        assert "cache" in engine and "applied_knobs" in engine


# -- dump-on-abort worlds (the acceptance cells) -------------------------------


def _abort_world_fn(steps):
    """Per-rank body (shipped by value): allreduce loop that catches the
    world fault and returns — the incident file is the artifact."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    try:
        for step in range(steps):
            hvd.allreduce(np.full((16,), float(rank + step + 1),
                                  np.float32),
                          average=False, name="flightrec.abort")
    except hvd.HorovodInternalError as exc:
        return {"rank": rank, "outcome": "escalated",
                "error_type": type(exc).__name__}
    hvd.shutdown()
    return {"rank": rank, "outcome": "healed"}


def _run_abort_world(tmp_path, monkeypatch, extra, steps=6):
    from horovod_tpu.runner import run

    env = {
        HOROVOD_NATIVE_CONTROLLER: "0",
        HOROVOD_NATIVE_CORE: "0",
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        HOROVOD_CHAOS: "",
        HOROVOD_GRAD_SENTRY: "off",
        HOROVOD_FLIGHTREC: "1",
        HOROVOD_FLIGHTREC_DIR: str(tmp_path),
        HOROVOD_FLIGHTREC_DUMP_TIMEOUT: "3",
        HOROVOD_RECONNECT_ATTEMPTS: "3",
        HOROVOD_RECONNECT_BACKOFF: "0.05",
        HOROVOD_RECONNECT_WINDOW: "1",
        HOROVOD_STALL_WARNING_TIME: "2",
        HOROVOD_STALL_SHUTDOWN_TIME: "4",
        **extra,
    }
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    try:
        return run(_abort_world_fn, args=(steps,), np=2,
                   timeout_s=180.0, start_timeout_s=120.0)
    except Exception:  # noqa: BLE001 - faulted worlds may fail the run
        return None


def _classified(tmp_path):
    files = sorted(glob.glob(os.path.join(str(tmp_path),
                                          "blackbox-*.json")))
    assert files, "escalated world left no incident file"
    docs = []
    for path in files:
        with open(path) as fh:
            docs.append(json.load(fh))
    return flightrec.classify_incident(flightrec.merge_incidents(docs))


@pytest.mark.parametrize("core", ["0", "1"])
def test_mp_kill_cell_names_the_dead_rank(tmp_path, monkeypatch, core):
    """drop/close chaos exhausts rank 1's reconnect budget: the incident
    classifier names the dead rank and the last agreed cycle — on both
    negotiation cores."""
    _run_abort_world(tmp_path, monkeypatch, {
        HOROVOD_NATIVE_CORE: core,
        HOROVOD_CHAOS: "close@rank1:msg6,refuse@relaunch:999"})
    report = _classified(tmp_path)
    assert report["verdict"].startswith("dead@rank1"), report
    assert isinstance(report["last_agreed_cycle"], int), report


@pytest.mark.parametrize("core", ["0", "1"])
def test_mp_nan_cell_names_the_injected_rank(tmp_path, monkeypatch, core):
    """nan@rank1 under sentry abort: the NaN implicates every rank
    post-combine; the classifier names rank 1 off its recorded chaos
    injection — on both negotiation cores."""
    _run_abort_world(tmp_path, monkeypatch, {
        HOROVOD_NATIVE_CORE: core,
        HOROVOD_CHAOS: "nan@rank1:msg3",
        HOROVOD_GRAD_SENTRY: "abort"})
    report = _classified(tmp_path)
    assert report["verdict"] == "nonfinite@rank1 step 3", report
    assert report["chaos_ranks"] == [1], report


def _hard_kill_world_fn(steps):
    """Per-rank body where rank 1 dies HARD (``os._exit``, no handshake,
    no exception handling): the launcher observes the nonzero exit and
    must hold its teardown for the evidence grace so rank 0's collector
    lands the dump (docs/blackbox.md §Limits)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    for step in range(steps):
        hvd.allreduce(np.full((16,), float(rank + step + 1), np.float32),
                      average=False, name="flightrec.hardkill")
        if step == 3 and rank == 1:
            os._exit(17)
    hvd.shutdown()
    return {"rank": rank, "outcome": "healed"}


def test_mp_hard_kill_grace_lands_the_dump(tmp_path, monkeypatch):
    """rank 1 os._exits mid-step (uncaught, nonzero — the path the
    launcher fail-fasts on): with the evidence grace armed, the
    surviving coordinator still writes a classifiable incident naming
    the dead rank before the LaunchError surfaces. With grace 0 (the
    suite-wide conftest pin) this world provably loses the dump — the
    grace is what makes a hard kill diagnosable."""
    from horovod_tpu.runner import run
    from horovod_tpu.runner.launcher import LaunchError

    env = {
        HOROVOD_NATIVE_CONTROLLER: "0",
        HOROVOD_NATIVE_CORE: "0",
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        HOROVOD_CHAOS: "",
        HOROVOD_GRAD_SENTRY: "off",
        HOROVOD_FLIGHTREC: "1",
        HOROVOD_FLIGHTREC_DIR: str(tmp_path),
        HOROVOD_FLIGHTREC_DUMP_TIMEOUT: "3",
        HOROVOD_FLIGHTREC_LAUNCH_GRACE: "10",
        HOROVOD_RECONNECT_WINDOW: "1",
    }
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    with pytest.raises(LaunchError) as excinfo:
        run(_hard_kill_world_fn, args=(6,), np=2,
            timeout_s=180.0, start_timeout_s=120.0)
    assert excinfo.value.rank == 1  # the original failure still surfaces
    report = _classified(tmp_path)
    assert report["verdict"].startswith("dead@rank1"), report
    assert isinstance(report["last_agreed_cycle"], int), report


def test_launch_grace_defaults_and_knob(monkeypatch, fresh_recorder):
    monkeypatch.setenv(HOROVOD_RECONNECT_WINDOW, "2")
    monkeypatch.setenv(HOROVOD_FLIGHTREC_DUMP_TIMEOUT, "3")
    monkeypatch.delenv(HOROVOD_FLIGHTREC_LAUNCH_GRACE, raising=False)
    assert flightrec.launch_grace_s() == 6.0  # window + timeout + 1
    monkeypatch.setenv(HOROVOD_FLIGHTREC_LAUNCH_GRACE, "0")
    assert flightrec.launch_grace_s() == 0.0
    monkeypatch.setenv(HOROVOD_FLIGHTREC_LAUNCH_GRACE, "7.5")
    assert flightrec.launch_grace_s() == 7.5
    monkeypatch.setenv(HOROVOD_RECONNECT_WINDOW, "60")
    monkeypatch.delenv(HOROVOD_FLIGHTREC_LAUNCH_GRACE, raising=False)
    assert flightrec.launch_grace_s() == 15.0  # capped


def test_launch_grace_zero_when_disabled(monkeypatch):
    monkeypatch.setenv(HOROVOD_FLIGHTREC, "0")
    monkeypatch.delenv(HOROVOD_FLIGHTREC_LAUNCH_GRACE, raising=False)
    flightrec.reset_for_tests()
    try:
        assert flightrec.launch_grace_s() == 0.0
    finally:
        flightrec.reset_for_tests()


def test_mp_clean_world_writes_nothing(tmp_path, monkeypatch):
    results = _run_abort_world(tmp_path, monkeypatch, {})
    assert results is not None and \
        all(r["outcome"] == "healed" for r in results), results
    assert glob.glob(os.path.join(str(tmp_path), "blackbox-*.json")) == []


def test_mp_native_controller_local_degrade(tmp_path, monkeypatch):
    """The native controller wire predates the flightrec RPC: each rank
    writes a rank-local dump, and the report tool still merges them into
    a classifiable incident."""
    pytest.importorskip("horovod_tpu.cc")
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip("native controller not built on this image")
    _run_abort_world(tmp_path, monkeypatch, {
        HOROVOD_NATIVE_CONTROLLER: "1",
        HOROVOD_CHAOS: "close@rank1:msg6,refuse@relaunch:999"})
    files = glob.glob(os.path.join(str(tmp_path), "blackbox-*.json"))
    assert files, "native-controller abort left no rank-local dump"
    assert all(".rank" in os.path.basename(p) for p in files), files
    report = _classified(tmp_path)
    assert "rank1" in report["verdict"] or \
        report["verdict"].startswith("abort"), report


@pytest.mark.slow
def test_dryrun_flightrec_certification():
    """The full 2-proc certification in a subprocess (both negotiation
    cores, nan cell, clean world, disabled knob)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_flightrec; "
         "dryrun_flightrec(); print('DRYRUN_FLIGHTREC_OK')"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_FLIGHTREC_OK" in proc.stdout
