"""Gradient numerics observatory tests (docs/tensorwatch.md).

Named past the 870 s tier-1 truncation point (ROADMAP note); the
``tensorwatch`` marker runs just this battery. Covers: the sampling
gate and its zero-allocation armed-idle path, the stats/SNR math
against NumPy references (numpy and jnp twins pinned equal), the
worst-K label cardinality cap, the evidence gate's block/admit/revert
loop down to the JSONL decision log, the merge_snapshots overflow-
bucket satellite, the report fold + tool contract, the disabled-path
HLO audit, and the 2-proc sampled-world bit-exactness acceptance on
both negotiation cores.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tracemalloc

import numpy as np
import pytest

from horovod_tpu.obs import tensorwatch as tw

pytestmark = pytest.mark.tensorwatch


@pytest.fixture(autouse=True)
def _fresh_gate():
    tw.reset_for_tests()
    yield
    tw.reset_for_tests()


# -- sampling gate -------------------------------------------------------------


class TestSamplingGate:
    def test_interval_gating(self):
        watch = tw.TensorWatch(3)
        sampled = []
        for _ in range(9):
            watch.begin_batch()
            sampled.append(watch.sampling)
        assert sampled == [False, False, True] * 3
        assert watch.ordinal == 9

    def test_from_config_disabled_is_none(self):
        from horovod_tpu.core.config import Config

        assert tw.from_config(Config()) is None
        cfg = Config(tensorwatch_interval_steps=4)
        watch = tw.from_config(cfg, size=2, rank=1)
        assert watch is not None and watch.interval == 4

    def test_armed_idle_path_allocation_free(self):
        """The flightrec bar: an armed observatory's NON-sampled batches
        are integer arithmetic only — no allocation growth over
        thousands of batches (interval 0 builds no object at all, so
        the disabled path is one `is not None` check)."""
        watch = tw.TensorWatch(1 << 30)
        watch.begin_batch()  # warm the attribute paths
        tracemalloc.start()
        before = tracemalloc.take_snapshot()
        for _ in range(2000):
            watch.begin_batch()
        after = tracemalloc.take_snapshot()
        tracemalloc.stop()
        stats = after.compare_to(before, "filename")
        grown = sum(s.size_diff for s in stats if s.size_diff > 0)
        # tracemalloc bookkeeping itself can show a few hundred bytes
        assert grown < 4096, f"armed-idle begin_batch allocated {grown}B"

    def test_watch_codecs_from_config(self):
        from horovod_tpu.core.config import Config

        assert tw.watch_codecs(Config()) == ()
        assert tw.watch_codecs(Config(compression="int8")) == ("int8",)
        cfg = Config(compression="fp16",
                     autotune_codecs=("int8", "fp8"))
        # cast codecs carry no decode leg; consent candidates do
        assert tw.watch_codecs(cfg) == ("int8", "fp8")


# -- stats / SNR math ----------------------------------------------------------


class TestStatsMath:
    def test_np_stats_reference(self):
        arr = np.array([0.0, 1.0, -2.0, 0.5, 8.0], np.float32)
        st = tw._np_tensor_stats(arr)
        assert st["elems"] == 5
        assert st["nnz"] == 4
        assert st["absmax"] == 8.0
        assert abs(st["norm2"] - float((arr.astype(np.float64) ** 2)
                                       .sum())) < 1e-9
        # log2 exponents: 0, 1, -1, 3 -> bins at offsets 24, 25, 23, 27
        hist = st["log2_hist"]
        assert hist[24] == 1 and hist[25] == 1 and hist[23] == 1 \
            and hist[27] == 1
        assert sum(hist) == 4
        # top-1 entry (8.0) holds 64/69.25 of the energy; k=1 for all
        # three fractions at n=5
        expect = 64.0 / float((arr.astype(np.float64) ** 2).sum())
        for key in ("0.1", "1", "10"):
            assert abs(st["topk"][key] - expect) < 1e-12

    def test_snr_db_definition(self):
        assert tw.snr_db(0.0, 1.0) == 0.0
        assert tw.snr_db(1.0, 0.0) == tw.SNR_CAP_DB
        assert abs(tw.snr_db(100.0, 1.0) - 20.0) < 1e-12
        # the cap also bounds absurdly clean measurements
        assert tw.snr_db(1e300, 1e-300) == tw.SNR_CAP_DB
        # non-finite power (NaN batch, f32 overflow) reports 0 dB —
        # conservative for the gate, never NaN/Infinity in the JSON
        assert tw.snr_db(float("nan"), 1.0) == 0.0
        assert tw.snr_db(1.0, float("nan")) == 0.0
        assert tw.snr_db(float("inf"), 1.0) == 0.0

    def test_nonfinite_sample_skipped_not_leaked(self):
        """The observatory is PRE-sentry by design, so NaN gradients
        reach sampled measurements — the tensor is skipped and counted,
        never a NaN in the table/gauges (the RFC-JSON surfaces)."""
        watch = tw.TensorWatch(1)
        watch.begin_batch()
        bad = np.array([1.0, np.nan, 2.0], np.float32)
        good = np.array([1.0, -2.0, 3.0], np.float32)
        watch.observe_batch(["bad", "good"], [bad, good], [bad, good])
        report = watch.report()
        assert "bad" not in report["tensors"]
        row = report["tensors"]["good"]
        assert math.isfinite(row["norm2"])
        # and the full JSON document stays RFC-parseable
        json.loads(json.dumps(report))

    def test_int8_roundtrip_vs_numpy_reference(self):
        """The codec's roundtrip_error against an INDEPENDENT reference
        implementation of the block math (docs/compression.md)."""
        from horovod_tpu.ops.compression import Compression

        rng = np.random.RandomState(7)
        x = (rng.randn(3000) * np.logspace(-2, 1, 3000)).astype(
            np.float32)
        size = 2
        codec = Compression.int8
        sp, ep = codec.roundtrip_error(x, size)
        # reference: pad to the codec's block geometry, quantize each
        # block with scale = absmax/127 (multiply by the reciprocal,
        # like the wire), round, clip, dequantize
        block, padded = codec.block_layout(x.size, size)
        flat = np.concatenate([x, np.zeros(padded - x.size, np.float32)])
        blocks = flat.reshape(-1, block)
        absmax = np.abs(blocks).max(axis=1)
        scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(
            np.float32)
        q = np.clip(np.round(blocks * (1.0 / scale)[:, None]),
                    -127, 127).astype(np.int8)
        deq = q.astype(np.float32) * scale[:, None]
        ref_sp = float((blocks.astype(np.float64) ** 2).sum())
        ref_ep = float(((deq - blocks).astype(np.float64) ** 2).sum())
        assert abs(sp - ref_sp) < 1e-6 * max(ref_sp, 1)
        assert abs(ep - ref_ep) < 1e-6 * max(ref_ep, 1)
        # and the SNR lands in the plausible int8 regime
        assert 25.0 < tw.snr_db(sp, ep) < 60.0

    def test_jnp_twin_matches_numpy(self):
        """ops.spmd.codec_roundtrip (the compiled probe's body) pinned
        equal to Compression.roundtrip_error — one definition."""
        import jax

        from horovod_tpu.ops.compression import Compression
        from horovod_tpu.ops.spmd import codec_roundtrip

        rng = np.random.RandomState(3)
        x = rng.randn(2000).astype(np.float32)
        for codec in (Compression.int8, Compression.fp8):
            sp_n, ep_n = codec.roundtrip_error(x, 4)
            sp_j, ep_j = jax.jit(
                lambda v, c=codec: codec_roundtrip(v, c, 4))(x)
            snr_n = tw.snr_db(sp_n, ep_n)
            snr_j = tw.snr_db(float(sp_j), float(ep_j))
            assert abs(snr_n - snr_j) < 0.05, (codec.codec_name,
                                               snr_n, snr_j)

    def test_plane_probes_match_numpy(self):
        """XlaDataPlane.tensorwatch_stats / codec_snr (the device-side
        scalar probes) agree with the host measurement."""
        import types

        import jax.numpy as jnp

        from horovod_tpu.ops.xla_plane import XlaDataPlane

        plane = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
        x = np.random.RandomState(11).randn(1500).astype(np.float32)
        st = plane.tensorwatch_stats(jnp.asarray(x))
        ref = tw._np_tensor_stats(x)
        assert st["elems"] == ref["elems"]
        assert st["nnz"] == ref["nnz"]
        assert st["log2_hist"] == ref["log2_hist"]
        assert abs(st["norm2"] - ref["norm2"]) < 1e-4 * ref["norm2"]
        for key in ("0.1", "1", "10"):
            assert abs(st["topk"][key] - ref["topk"][key]) < 1e-5
        sp, ep = plane.codec_snr(jnp.asarray(x), "int8")
        ref_snr = tw._np_codec_snr(x, "int8", 1)
        assert abs(tw.snr_db(sp, ep) - ref_snr) < 0.05
        # the pre-reduce side's scalar-only probe (never the full stats
        # program twice): one norm², pinned to the numpy twin
        n2 = plane.tensorwatch_norm2(jnp.asarray(x))
        ref_n2 = tw._np_norm2(x)
        assert abs(n2 - ref_n2) < 1e-4 * ref_n2

    def test_quantized_codec_tags_cross_pinned(self):
        from horovod_tpu.ops.compression import Compression

        for tag in tw.QUANTIZED_CODECS:
            assert getattr(Compression.lookup(tag), "quantized", False)
        # and no quantized codec is missing from the copy
        for name in ("none", "fp16", "bf16", "int8", "fp8"):
            codec = Compression.lookup(name)
            if getattr(codec, "quantized", False):
                assert name in tw.QUANTIZED_CODECS


# -- cardinality cap -----------------------------------------------------------


class TestCardinality:
    def test_worst_k_label_cap(self):
        watch = tw.TensorWatch(1, worst_k=3)
        names = [f"tw.cap.{i}" for i in range(40)]
        arrs = [np.full(16, float(i + 1), np.float32)
                for i in range(40)]
        watch.begin_batch()
        assert watch.sampling
        watch.observe_batch(names, arrs, arrs, "none")
        # the full table keeps everything; labels stay bounded
        assert len(watch.report()["tensors"]) == 40
        assert len(watch._labeled) <= 4 * 3
        from horovod_tpu.obs.registry import registry

        fam = registry().snapshot()[tw.FAMILY_TENSOR_NORM2]
        ours = [s for s in fam["samples"]
                if s["labels"].get("tensor", "").startswith("tw.cap.")]
        assert 0 < len(ours) <= 4 * 3

    def test_retired_tensor_pins_to_zero(self):
        watch = tw.TensorWatch(1, worst_k=1)
        watch.begin_batch()
        watch.observe_batch(["tw.ret.a"],
                            [np.full(8, 2.0, np.float32)],
                            [np.full(8, 2.0, np.float32)], "none")
        watch.begin_batch()
        # a bigger tensor takes the single worst slot; 'a' retires to 0
        watch.observe_batch(["tw.ret.b"],
                            [np.full(8, 99.0, np.float32)],
                            [np.full(8, 99.0, np.float32)], "none")
        from horovod_tpu.obs.registry import registry

        fam = registry().snapshot()[tw.FAMILY_TENSOR_NORM2]
        values = {s["labels"]["tensor"]: s["value"]
                  for s in fam["samples"]}
        assert values["tw.ret.a"] == 0
        assert values["tw.ret.b"] > 0


# -- merge_snapshots overflow bucket (the PR satellite) ------------------------


class TestOverflowBucketFold:
    @staticmethod
    def _hist_snap(buckets):
        return {"m": {"type": "histogram", "help": "", "label_names": [],
                      "samples": [{"bounds": [1.0, 2.0],
                                   "buckets": list(buckets),
                                   "sum": float(sum(buckets)),
                                   "count": sum(buckets),
                                   "labels": {}}]}}

    def test_world_fold_preserves_overflow_distinct(self):
        """The +Inf overflow bucket (the slot past the last bound, whose
        quantiles deliberately read None since PR 6) must fold as its
        own slot — never blended into the finite buckets."""
        from horovod_tpu.obs.registry import merge_snapshots

        merged = merge_snapshots([self._hist_snap([1, 2, 7]),
                                  self._hist_snap([3, 4, 11])])
        sample = merged["m"]["samples"][0]
        assert sample["buckets"] == [4, 6, 18]
        assert len(sample["buckets"]) == len(sample["bounds"]) + 1

    def test_truncated_bucket_list_fails_loudly(self):
        """A malformed snapshot whose bucket list lost the overflow slot
        must fail the fold, not let zip() silently drop the counts."""
        from horovod_tpu.obs.registry import merge_snapshots

        with pytest.raises(ValueError, match="overflow"):
            merge_snapshots([self._hist_snap([1, 2, 7]),
                             self._hist_snap([3, 4])])

    def test_live_histogram_overflow_survives_fold(self):
        from horovod_tpu.obs.registry import Registry, merge_snapshots

        regs = [Registry(), Registry()]
        for i, reg in enumerate(regs):
            h = reg.histogram("tw_overflow_probe", "", buckets=(0.5,))
            h.observe(0.1)       # finite bucket
            h.observe(100.0 + i)  # overflow bucket
        merged = merge_snapshots([r.snapshot() for r in regs])
        sample = merged["tw_overflow_probe"]["samples"][0]
        assert sample["buckets"] == [2, 2]  # [<=0.5, +Inf] per-rank sums


# -- evidence gate -------------------------------------------------------------


class TestEvidenceGate:
    def test_certify_needs_full_window(self):
        gate = tw.EvidenceGate(20.0, 3)
        gate.observe("int8", 30.0)
        gate.observe("int8", 30.0)
        assert not gate.allows("int8")
        gate.observe("int8", 30.0)
        assert gate.allows("int8")
        record = gate.evidence_record("int8")
        assert record["certified"] and record["certified_at_sample"] == 3
        assert record["snr_db_window"] == [30.0, 30.0, 30.0]

    def test_floor_miss_resets_certification(self):
        gate = tw.EvidenceGate(20.0, 2)
        gate.observe("int8", 25.0)
        gate.observe("int8", 10.0)  # miss BEFORE any certification
        gate.observe("int8", 25.0)
        assert not gate.allows("int8")  # window holds [10, 25]
        # and a pre-certification dip never latches a collapse
        assert not gate.take_collapse("int8")
        gate.observe("int8", 25.0)
        assert gate.allows("int8")

    def test_collapse_latches_only_when_certified(self):
        gate = tw.EvidenceGate(20.0, 2)
        for _ in range(2):
            gate.observe("int8", 40.0)
        assert gate.allows("int8")
        gate.observe("int8", 5.0)
        assert not gate.allows("int8")
        assert gate.take_collapse("int8")
        assert not gate.take_collapse("int8")  # consumed exactly once

    def test_recertification_clears_stale_collapse(self):
        gate = tw.EvidenceGate(20.0, 2)
        for _ in range(2):
            gate.observe("int8", 40.0)
        gate.observe("int8", 5.0)  # collapse latched
        for _ in range(2):
            gate.observe("int8", 40.0)  # re-certifies
        assert gate.allows("int8")
        assert not gate.take_collapse("int8")

    def test_codec_knob_name_cross_pinned(self):
        from horovod_tpu.tune.policy import KNOB_CODEC

        assert tw.CODEC_KNOB == KNOB_CODEC

    def _policy(self, sink, gate):
        from horovod_tpu.tune.policy import KNOB_CODEC, Knob, \
            TuningPolicy

        return TuningPolicy(
            [Knob("fusion_threshold_bytes", (1,), 0, pinned=True),
             Knob(KNOB_CODEC, ("none", "int8"), 0)],
            window=1, cooldown=0, decision_sink=sink.append,
            propose_gate=tw.PolicyGate(gate))

    def test_policy_blocks_until_certified_then_admits(self):
        from horovod_tpu.tune.policy import KNOB_CODEC

        sink = []
        gate = tw.EvidenceGate(20.0, 3)
        policy = self._policy(sink, gate)
        for _ in range(8):
            decision = policy.observe(1000, 10)
            assert decision is None or decision.knob != KNOB_CODEC
        assert not any(r.get("knob") == KNOB_CODEC for r in sink)
        for _ in range(3):
            gate.observe("int8", 42.0)
        admitted = None
        for _ in range(8):
            decision = policy.observe(1000, 10)
            if decision is not None and decision.knob == KNOB_CODEC:
                admitted = decision
                break
        assert admitted is not None and admitted.value == "int8"
        record = [r for r in sink if r.get("knob") == KNOB_CODEC][-1]
        assert record["evidence"]["certified"]
        assert record["evidence"]["certified_at_sample"] >= 3

    def test_collapse_forces_audited_revert(self):
        from horovod_tpu.tune.policy import KNOB_CODEC

        sink = []
        gate = tw.EvidenceGate(20.0, 2)
        policy = self._policy(sink, gate)
        pg = tw.PolicyGate(gate)
        for _ in range(2):
            gate.observe("int8", 42.0)
        while True:  # drive until the codec move lands
            decision = policy.observe(1000, 10)
            if decision is not None and decision.knob == KNOB_CODEC:
                break
        assert policy.config()[KNOB_CODEC] == "int8"
        gate.observe("int8", 3.0)  # in-flight collapse
        forced = pg.maybe_revert(policy)
        assert forced is not None and forced.action == "revert"
        assert forced.config[KNOB_CODEC] == "none"
        assert policy.config()[KNOB_CODEC] == "none"
        assert policy.reverts == 1
        record = sink[-1]
        assert record["action"] == "revert" and "evidence" in record
        # consumed: no second forced revert, and the knob stays put
        assert pg.maybe_revert(policy) is None

    def test_no_gate_keeps_consent_only_behavior(self):
        """Observatory off = the PR 7 behavior byte-identically: the
        consented codec is proposed on plain consent."""
        from horovod_tpu.tune.policy import KNOB_CODEC, Knob, \
            TuningPolicy

        policy = TuningPolicy(
            [Knob("fusion_threshold_bytes", (1,), 0, pinned=True),
             Knob(KNOB_CODEC, ("none", "int8"), 0)],
            window=1, cooldown=0)
        moved = False
        for _ in range(4):
            decision = policy.observe(1000, 10)
            if decision is not None and decision.knob == KNOB_CODEC:
                moved = True
                break
        assert moved

    def test_autotuner_facade_wires_gate(self, monkeypatch, tmp_path):
        from horovod_tpu.core.config import (
            Config,
            HOROVOD_TENSORWATCH_INTERVAL,
        )
        from horovod_tpu.ops.autotuner import Autotuner

        # disarmed observatory: no gate object on the policy
        monkeypatch.delenv(HOROVOD_TENSORWATCH_INTERVAL, raising=False)
        tw.reset_for_tests()
        tuner = Autotuner(Config(autotune=True), extended=True)
        try:
            assert tuner._gate is None
            assert tuner._backend._propose_gate is None
        finally:
            tuner.close()
        # armed: the facade builds the PolicyGate from the env singleton
        monkeypatch.setenv(HOROVOD_TENSORWATCH_INTERVAL, "2")
        tw.reset_for_tests()
        tuner = Autotuner(
            Config(autotune=True, tensorwatch_interval_steps=2,
                   autotune_codecs=("int8",)), extended=True)
        try:
            assert tuner._gate is not None
            assert tuner._backend._propose_gate is tuner._gate
        finally:
            tuner.close()

    def test_engineless_host_degrades_to_consent_only(self, monkeypatch):
        """A non-member controller host (start_subset_service) runs no
        engine, so nothing in its process could ever feed the evidence
        gate — armed gating there would block the consented codec for
        the life of the job. It degrades to consent-only, warned once
        (the established degrade pattern)."""
        import logging

        from horovod_tpu.core.config import (
            Config,
            HOROVOD_TENSORWATCH_INTERVAL,
        )
        from horovod_tpu.core.logging import LOG
        from horovod_tpu.ops.autotuner import Autotuner

        class _Cap(logging.Handler):
            # LOG has propagate=False: caplog never sees its records
            # (the test_optimizer precedent) — attach directly
            def __init__(self):
                super().__init__(level=logging.WARNING)
                self.messages = []

            def emit(self, record):
                self.messages.append(record.getMessage())

        monkeypatch.setenv(HOROVOD_TENSORWATCH_INTERVAL, "2")
        tw.reset_for_tests()
        cap = _Cap()
        LOG.addHandler(cap)
        try:
            tuner = Autotuner(
                Config(autotune=True, tensorwatch_interval_steps=2,
                       autotune_codecs=("int8",)), extended=True,
                local_observatory=False)
            try:
                assert tuner._gate is None
                assert tuner._backend._propose_gate is None
                assert any("no engine to feed" in m
                           for m in cap.messages)
            finally:
                tuner.close()
        finally:
            LOG.removeHandler(cap)

    def test_from_config_gate_uses_resolved_knobs(self):
        """The gate certifies against the RESOLVED Config floor/window,
        not a second env read — a programmatic Config must not leave
        the watch's floor-miss counter and the gate's certification
        disagreeing about where the floor is."""
        from horovod_tpu.core.config import Config

        cfg = Config(tensorwatch_interval_steps=1,
                     tensorwatch_snr_floor_db=33.0,
                     tensorwatch_snr_window=2)
        watch = tw.from_config(cfg)
        gate = tw.evidence_gate()
        assert watch._gate is gate
        assert gate is not None
        assert gate.floor_db == 33.0 and gate.window == 2


# -- report fold + tool --------------------------------------------------------


def _fam(ftype, samples):
    return {"type": ftype, "help": "", "label_names": [],
            "samples": samples}


def _rank_families(rank, snr, prenorm):
    def g(value, **labels):
        return {"value": value, "labels": labels}

    return {
        tw.FAMILY_SAMPLES: _fam("counter", [g(5)]),
        tw.FAMILY_TENSOR_NORM2: _fam("gauge", [
            g(100.0, tensor="w1"), g(0, tensor="retired")]),
        tw.FAMILY_TENSOR_PRENORM2: _fam("gauge", [
            g(prenorm, tensor="w1")]),
        tw.FAMILY_TENSOR_SNR: _fam("gauge", [g(snr, tensor="w1")]),
        tw.FAMILY_CODEC_SNR: _fam("gauge", [g(snr, codec="int8")]),
        tw.FAMILY_TOPK: _fam("gauge", [
            g(0.4, k="0.1"), g(0.7, k="1"), g(0.95, k="10")]),
    }


class TestReportFold:
    def test_fold_spread_and_worst_snr(self):
        ranks = {0: _rank_families(0, 35.0, 10.0),
                 1: _rank_families(1, 31.5, 40.0)}
        report = tw.build_tensor_report(ranks)
        assert not report["degraded"]
        assert report["samples"] == 10
        row = report["tensors"][0]
        assert row["tensor"] == "w1"
        assert row["worst_snr_db"] == 31.5  # min across ranks
        assert abs(row["spread"] - 4.0) < 1e-9  # 40/10 skew
        assert report["codec_snr_db"]["int8"] == 31.5
        assert report["topk_mass"]["10"] == 0.95
        # zero-valued labels mean "left the worst set" and are skipped
        assert all(r["tensor"] != "retired" for r in report["tensors"])

    def test_fold_degrades_without_families(self):
        report = tw.build_tensor_report({0: {}})
        assert report["degraded"] and report["tensors"] == []

    def test_fold_loads_without_the_package(self):
        """The exec-fallback contract (the straggler_report precedent):
        tensorwatch.py's module level is stdlib-only, so the fold loads
        from the FILE on jax-less boxes."""
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "horovod_tpu", "obs", "tensorwatch.py")
        spec = importlib.util.spec_from_file_location("_tw_fold", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        report = mod.build_tensor_report(
            {0: _rank_families(0, 30.0, 1.0)})
        assert report["tensors"][0]["tensor"] == "w1"

    def test_tool_final_line_json_contract(self, tmp_path):
        doc = {"world": {},
               "ranks": {"0": _rank_families(0, 28.0, 4.0),
                         "1": _rank_families(1, 33.0, 1.0)}}
        snap = tmp_path / "snap.json"
        snap.write_text(json.dumps(doc))
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "tensorwatch_report.py"),
             str(snap)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["tensors"][0]["tensor"] == "w1"
        assert report["tensors"][0]["worst_snr_db"] == 28.0
        assert "numerics observatory" in proc.stdout


# -- disabled-path HLO audit ---------------------------------------------------


class TestHLOAudit:
    def test_reduce_programs_unchanged_when_armed(self, monkeypatch):
        """The observatory's measurement programs are SEPARATE compiles:
        arming it must not add a single scalar output to the fused
        reduce or reduce+apply programs (the disabled-path overhead
        contract, acceptance-pinned)."""
        import types

        from horovod_tpu.core.config import HOROVOD_TENSORWATCH_INTERVAL
        from horovod_tpu.ops.fused_apply import ApplyRule
        from horovod_tpu.ops.xla_plane import XlaDataPlane

        monkeypatch.delenv(HOROVOD_TENSORWATCH_INTERVAL, raising=False)
        plane_off = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
        hlo_off = plane_off.reduce_donation_hlo(4096)
        apply_off = plane_off.reduce_apply_hlo(4096, ApplyRule("sgd", 0.1))
        monkeypatch.setenv(HOROVOD_TENSORWATCH_INTERVAL, "1")
        tw.reset_for_tests()
        plane_on = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
        assert plane_on.reduce_donation_hlo(4096) == hlo_off
        assert plane_on.reduce_apply_hlo(
            4096, ApplyRule("sgd", 0.1)) == apply_off


# -- live size-1 engine --------------------------------------------------------


class TestLiveEngine:
    def test_size1_sampled_engine_and_v1_tensors(self, monkeypatch):
        from horovod_tpu.core.config import (
            HOROVOD_AUTOTUNE_CODECS,
            HOROVOD_TENSORWATCH_INTERVAL,
        )

        monkeypatch.setenv(HOROVOD_TENSORWATCH_INTERVAL, "1")
        monkeypatch.setenv(HOROVOD_AUTOTUNE_CODECS, "int8")
        tw.reset_for_tests()
        import horovod_tpu as hvd

        hvd.init()
        try:
            rng = np.random.RandomState(0)
            for step in range(3):
                hvd.allreduce(rng.randn(600).astype(np.float32),
                              name="tw.live", average=False)
            report = hvd.tensor_report()
            assert report["enabled"] and report["samples"] >= 1
            row = report["tensors"]["tw.live"]
            assert math.isfinite(row["snr_db"]["int8"])
            assert 0 < row["topk"]["0.1"] <= row["topk"]["1"] \
                <= row["topk"]["10"] <= 1.0
            assert sum(row["log2_hist"]) == row["nnz"]
            assert report["gate"] is not None
            from horovod_tpu.obs.exposition import metrics_routes

            routes = metrics_routes(lambda: {"world": {}, "ranks": {}})
            resp = routes[("GET", "/v1/tensors")](None, None, None)
            doc = json.loads(resp.body)
            assert doc["enabled"] and "tw.live" in doc["tensors"]
        finally:
            hvd.shutdown()


# -- 2-proc acceptance ---------------------------------------------------------


def _tw_world_fn(steps):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank = hvd.rank()
    outs = []
    for step in range(steps):
        for i in range(2):
            out = hvd.allreduce(
                (np.arange(600, dtype=np.float32) - 300.0)
                * float((rank + 1) * (i + 1) * (step + 1)) * 1e-3,
                average=False, name=f"tw.mp.{i}")
            outs.append(np.asarray(out).tolist())
    watch = get_engine()._tensorwatch
    report = watch.report() if watch is not None else None
    hvd.shutdown()
    return {"rank": rank, "results": outs, "report": report}


def _run_world(np_, steps=6, **env):
    from horovod_tpu.runner import run

    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0", **env}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        return run(_tw_world_fn, args=(steps,), np=np_,
                   timeout_s=180.0, start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _assert_watch_world(watched, plain, n_ranks):
    by_rank_w = {r["rank"]: r for r in watched}
    by_rank_p = {r["rank"]: r for r in plain}
    for rank in range(n_ranks):
        # the acceptance pin: sampling is bit-exactness-NEUTRAL
        assert by_rank_w[rank]["results"] == by_rank_p[rank]["results"]
        assert by_rank_p[rank]["report"] is None
        report = by_rank_w[rank]["report"]
        assert report is not None and report["samples"] >= 1
        # interval 2 over one-batch-per-allreduce cycles: exactly every
        # second batch sampled (the gating pin), and every sampled
        # tensor carries finite SNR + a monotone coverage curve
        assert report["batches"] == 2 * report["samples"]
        assert report["tensors"], report
        for name, row in report["tensors"].items():
            assert name.startswith("tw.mp."), name
            assert math.isfinite(row["snr_db"]["int8"])
            assert row["snr_db"]["int8"] > 0
            assert 0 < row["topk"]["0.1"] <= row["topk"]["1"] \
                <= row["topk"]["10"] <= 1.0


def test_mp_sampled_world_bit_exact_python_core():
    watched = _run_world(2, HOROVOD_TENSORWATCH_INTERVAL_STEPS="2",
                         HOROVOD_AUTOTUNE_CODECS="int8",
                         HOROVOD_NATIVE_CORE="0")
    plain = _run_world(2, HOROVOD_TENSORWATCH_INTERVAL_STEPS="0",
                       HOROVOD_NATIVE_CORE="0")
    _assert_watch_world(watched, plain, 2)


def test_mp_sampled_world_bit_exact_native_core():
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native core unavailable: {cc.load_error()}")
    watched = _run_world(2, HOROVOD_TENSORWATCH_INTERVAL_STEPS="2",
                         HOROVOD_AUTOTUNE_CODECS="int8",
                         HOROVOD_NATIVE_CORE="1")
    plain = _run_world(2, HOROVOD_TENSORWATCH_INTERVAL_STEPS="0",
                       HOROVOD_NATIVE_CORE="1")
    _assert_watch_world(watched, plain, 2)


@pytest.mark.slow
def test_dryrun_tensorwatch_subprocess():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_tensorwatch; "
         "dryrun_tensorwatch()"],
        cwd=repo, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "tensorwatch OK" in proc.stderr
