"""Negotiator unit tests: multi-rank coordination logic without processes.

Covers ConstructResponse error semantics (``test_torch.py:270-366``: ranks
submitting mismatched shapes/dtypes/ops/roots must produce errors on all
ranks), fusion batching, and allgather size collection — directly against
the state machine the TCP controller serves.
"""

import numpy as np
import pytest

from horovod_tpu.ops.controller import Negotiator as PyNegotiator
from horovod_tpu.ops.messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
    ResponseType,
)


def _native_negotiator(size, threshold):
    import horovod_tpu.cc as cc

    if not cc.available():
        pytest.skip(f"native core unavailable: {cc.load_error()}")
    return cc.NativeNegotiator(size, threshold)


@pytest.fixture(params=["python", "native"])
def Negotiator(request):
    """Both negotiation cores must satisfy the same behavior contract,
    including identical error strings."""
    if request.param == "python":
        return PyNegotiator
    return _native_negotiator


def _req(rank, name, op=RequestType.ALLREDUCE, dtype=DataType.FLOAT32,
         shape=(4, 4), root=-1):
    return Request(request_rank=rank, request_type=op, tensor_name=name,
                   tensor_type=dtype, tensor_shape=tuple(shape),
                   root_rank=root)


def _negotiate(negotiator, *request_lists):
    for rl in request_lists:
        negotiator.add_request_list(rl)
    return negotiator.construct_response_list()


def test_not_ready_until_all_ranks(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(n, RequestList(0, [_req(0, "t")]))
    assert out.responses == []
    out = _negotiate(n, RequestList(1, [_req(1, "t")]))
    assert len(out.responses) == 1
    assert out.responses[0].response_type == ResponseType.ALLREDUCE
    assert out.responses[0].tensor_names == ["t"]


def test_mismatched_shape_error(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "t", shape=(4, 4))]),
        RequestList(1, [_req(1, "t", shape=(4, 5))]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched allreduce tensor shapes" in resp.error_message


def test_mismatched_dtype_error(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "t", dtype=DataType.FLOAT32)]),
        RequestList(1, [_req(1, "t", dtype=DataType.FLOAT64)]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched data types" in resp.error_message


def test_mismatched_op_error(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "t", op=RequestType.ALLREDUCE)]),
        RequestList(1, [_req(1, "t", op=RequestType.ALLGATHER, shape=(2, 4))]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched collective operations" in resp.error_message


def test_broadcast_root_mismatch_error(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "t", op=RequestType.BROADCAST, root=0)]),
        RequestList(1, [_req(1, "t", op=RequestType.BROADCAST, root=1)]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ERROR
    assert "root rank" in resp.error_message


def test_allgather_ragged_sizes(Negotiator):
    n = Negotiator(3, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "g", op=RequestType.ALLGATHER, shape=(2, 4))]),
        RequestList(1, [_req(1, "g", op=RequestType.ALLGATHER, shape=(5, 4))]),
        RequestList(2, [_req(2, "g", op=RequestType.ALLGATHER, shape=(1, 4))]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ALLGATHER
    assert resp.tensor_sizes == [2, 5, 1]  # rank-ordered recvcounts


def test_allgather_trailing_dim_mismatch(Negotiator):
    n = Negotiator(2, 1 << 26)
    out = _negotiate(
        n,
        RequestList(0, [_req(0, "g", op=RequestType.ALLGATHER, shape=(2, 4))]),
        RequestList(1, [_req(1, "g", op=RequestType.ALLGATHER, shape=(2, 5))]))
    (resp,) = out.responses
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched allgather tensor shapes" in resp.error_message


def test_fusion_batches_same_dtype_under_threshold(Negotiator):
    # threshold fits exactly two 4x4 f32 tensors (128 bytes)
    n = Negotiator(1, 128)
    out = _negotiate(n, RequestList(0, [
        _req(0, "a"), _req(0, "b"), _req(0, "c"),
    ]))
    batches = [r.tensor_names for r in out.responses]
    assert batches == [["a", "b"], ["c"]]


def test_fusion_not_across_dtypes(Negotiator):
    n = Negotiator(1, 1 << 26)
    out = _negotiate(n, RequestList(0, [
        _req(0, "a", dtype=DataType.FLOAT32),
        _req(0, "b", dtype=DataType.FLOAT64),
        _req(0, "c", dtype=DataType.FLOAT32),
    ]))
    batches = [r.tensor_names for r in out.responses]
    assert batches == [["a"], ["b"], ["c"]]


def test_fusion_not_across_ops(Negotiator):
    n = Negotiator(1, 1 << 26)
    out = _negotiate(n, RequestList(0, [
        _req(0, "a"),
        _req(0, "g", op=RequestType.ALLGATHER, shape=(2, 2)),
        _req(0, "b"),
    ]))
    types = [r.response_type for r in out.responses]
    assert types == [ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                     ResponseType.ALLREDUCE]


def test_shutdown_propagates(Negotiator):
    n = Negotiator(2, 1 << 26)
    n.add_request_list(RequestList(0, [], shutdown=True))
    n.add_request_list(RequestList(1, []))
    out = n.construct_response_list()
    assert out.shutdown
