"""In-process bench.py units — cheap pins that belong in the quick tier
(tests/test_bench.py is soak-marked wholesale: every test there executes
bench.py in a subprocess)."""

import functools
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@functools.cache
def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = bench
    spec.loader.exec_module(bench)
    return bench


def test_scan_cost_model_check_cpu():
    """Scan-mode MFU rests on cost_analysis() counting a lax.scan body
    once, not times the trip count; bench.py verifies that at runtime
    before attaching MFU fields (round-4 advisor). The check must answer
    True on this backend — a full scan-mode bench run on CPU (~9 min of
    ResNet-50 AOT compile) confirmed the end-to-end row carries
    scan_batches + tflops_per_device; this pins the gate cheaply, so a
    JAX upgrade that breaks the assumption surfaces in the quick tier."""
    bench = _load_bench()
    messages = []
    assert bench._scan_cost_counts_body_once(messages.append) is True, \
        messages
    assert not messages  # no "omitting MFU" path taken


def test_git_head_matches_shared_helper():
    """bench.py's _git_head must stay a thin delegate of the shared
    provenance helper (one sha-stamping implementation for every capture
    entry point)."""
    from horovod_tpu.core.provenance import git_head_sha

    bench = _load_bench()
    assert bench._git_head() == git_head_sha(_ROOT)
    assert bench._git_head()  # this repo is a git checkout


def test_host_init_cached_roundtrip(tmp_path):
    """host_init_cached: build→write, hit without rebuilding, corrupt
    entry rebuilds, empty path disables. The cache exists so a bench
    attempt's first accelerator touch lands seconds after the preflight
    probe instead of after a ~90s host init (round-5: the tunnel's
    healthy windows can be shorter than the init)."""
    import numpy as np

    from horovod_tpu.core.platform import host_init_cached

    path = str(tmp_path / "sub" / "entry.pkl")  # parent dir auto-created
    calls = []

    def make():
        calls.append(1)
        return {"w": np.arange(4.0, dtype=np.float32)}

    logs = []
    out1 = host_init_cached(path, make, log=logs.append)
    assert len(calls) == 1 and os.path.exists(path)
    assert any("cache written" in m for m in logs)

    out2 = host_init_cached(path, make, log=logs.append)
    assert len(calls) == 1  # hit: make() not rerun
    np.testing.assert_array_equal(out1["w"], out2["w"])
    assert any("cache hit" in m for m in logs)

    with open(path, "wb") as f:
        f.write(b"not a pickle")
    out3 = host_init_cached(path, make, log=logs.append)
    assert len(calls) == 2  # corrupt: rebuilt, not crashed
    np.testing.assert_array_equal(out1["w"], out3["w"])
    assert any("unreadable" in m for m in logs)

    host_init_cached("", make, log=logs.append)
    assert len(calls) == 3  # disabled: no caching, still builds


def test_init_cache_path_policy(monkeypatch):
    """The shared key policy (core.platform.init_cache_path): knob
    disables/redirects, and the hash covers extra_sources so the
    synthesize/init code that generates the arrays invalidates its own
    entries, not only the model zoo."""
    monkeypatch.delenv("HOROVOD_BENCH_INIT_CACHE", raising=False)
    bench = _load_bench()

    class A:
        model = "resnet50"

    args = A()
    p1 = bench._init_cache_path(args, 32, 224)
    assert p1.endswith(".pkl") and "resnet50_gb32_s224" in p1

    monkeypatch.setenv("HOROVOD_BENCH_INIT_CACHE", "0")
    assert bench._init_cache_path(args, 32, 224) == ""

    monkeypatch.setenv("HOROVOD_BENCH_INIT_CACHE", "/tmp/elsewhere")
    p2 = bench._init_cache_path(args, 32, 224)
    assert p2.startswith("/tmp/elsewhere/")
    # same config+sources -> same basename regardless of directory
    assert os.path.basename(p2) == os.path.basename(p1)

    # extra_sources participate in the digest: a different caller file
    # (different generating code) must produce a different entry
    from horovod_tpu.core.platform import init_cache_path

    monkeypatch.delenv("HOROVOD_BENCH_INIT_CACHE", raising=False)
    here = os.path.abspath(__file__)
    p3 = init_cache_path("resnet50_gb32_s224", extra_sources=[here])
    assert os.path.basename(p3) != os.path.basename(p1)
