"""In-process bench.py units — cheap pins that belong in the quick tier
(tests/test_bench.py is soak-marked wholesale: every test there executes
bench.py in a subprocess)."""

import functools
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@functools.cache
def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = bench
    spec.loader.exec_module(bench)
    return bench


def test_scan_cost_model_check_cpu():
    """Scan-mode MFU rests on cost_analysis() counting a lax.scan body
    once, not times the trip count; bench.py verifies that at runtime
    before attaching MFU fields (round-4 advisor). The check must answer
    True on this backend — a full scan-mode bench run on CPU (~9 min of
    ResNet-50 AOT compile) confirmed the end-to-end row carries
    scan_batches + tflops_per_device; this pins the gate cheaply, so a
    JAX upgrade that breaks the assumption surfaces in the quick tier."""
    bench = _load_bench()
    messages = []
    assert bench._scan_cost_counts_body_once(messages.append) is True, \
        messages
    assert not messages  # no "omitting MFU" path taken


def test_git_head_matches_shared_helper():
    """bench.py's _git_head must stay a thin delegate of the shared
    provenance helper (one sha-stamping implementation for every capture
    entry point)."""
    from horovod_tpu.core.provenance import git_head_sha

    bench = _load_bench()
    assert bench._git_head() == git_head_sha(_ROOT)
    assert bench._git_head()  # this repo is a git checkout
