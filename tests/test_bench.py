"""bench.py is the driver's perf artifact — it must always run end to end.

Round-1 postmortem: the bench had never executed before the driver ran it,
and it died inside ``hvd.init()`` with zero measured numbers. This test
executes the REAL bench script (tiny sizes, platform pinned to CPU, the
preflight skipped via its documented knob) and asserts the machine-readable
result line, so any refactor that breaks the artifact fails CI instead of
the round.
"""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_end_to_end_cpu(tmp_path):
    """One CPU run covers the whole artifact: the result line (including
    the MFU additions — achieved TFLOP/s from the compiled module's cost
    analysis; mfu_pct only appears on real accelerators) and the
    HOROVOD_BENCH_DUMP_HLO audit dump, so the multi-minute AOT compile is
    paid once."""
    hlo_path = str(tmp_path / "step_hlo.txt")
    bootstrap = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys, runpy; "
        "sys.argv = ['bench.py', '--batch-size', '2', "
        "'--num-warmup-batches', '1', '--num-batches-per-iter', '1', "
        "'--num-iters', '1']; "
        f"runpy.run_path({os.path.join(_ROOT, 'bench.py')!r}, "
        "run_name='__main__')"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"HOROVOD_BENCH_PREFLIGHT": "0",
                "HOROVOD_BENCH_DUMP_HLO": hlo_path})
    result = subprocess.run(
        [sys.executable, "-c", bootstrap], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"bench.py failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["metric"] == \
        "resnet50_synthetic_train_images_per_sec_per_device"
    assert line["value"] > 0
    assert line["unit"] == "img/s"
    assert isinstance(line["vs_baseline"], float)
    assert line["tflops_per_device"] > 0
    assert "mfu_pct" not in line  # meaningless on CPU, by design
    with open(hlo_path) as f:
        hlo = f.read()
    assert "ENTRY" in hlo or "HloModule" in hlo


def test_onchip_path_bench_cpu():
    """The single-device residency bench (docs/benchmarks.md) must run and
    produce its comparison row."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["HOROVOD_BENCH_PLATFORM"] = "cpu"
    result = subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "benchmarks", "onchip_path_bench.py"),
         "--tensors", "8", "--elems", "1024", "--rounds", "3"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, result.stderr
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["host_tensors_per_s"] > 0
    assert line["onchip_tensors_per_s"] > 0


def test_bench_supervised_path_cpu():
    """The driver-facing path: supervisor parent + measurement child.

    Round-2 postmortem: the tunnel wedged AFTER a clean preflight, inside
    the first compile — so the measurement itself must run in a killable,
    retryable child. This exercises that exact topology on CPU (preflight
    skipped, supervision forced on, child pinned via
    HOROVOD_BENCH_PLATFORM) and asserts the JSON line is relayed through
    the parent."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"HOROVOD_BENCH_PREFLIGHT": "0",
                "HOROVOD_BENCH_SUPERVISE": "1",
                "HOROVOD_BENCH_PLATFORM": "cpu"})
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--batch-size", "2", "--num-warmup-batches", "1",
         "--num-batches-per-iter", "1", "--num-iters", "1"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"bench.py supervised failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    assert "[supervise 1/" in result.stderr
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["value"] > 0


def test_preflight_nonfatal_returns_none(monkeypatch):
    """The supervisor's inter-attempt probe (after SIGKILLing a hung
    child, the tunnel lease can take a while to clear) must NOT exit the
    process when the backend stays down — the last measurement attempt
    still deserves its chance. Probes are mocked: this test must never
    touch a real accelerator."""
    import types

    sys.path.insert(0, _ROOT)
    try:
        import bench
    finally:
        sys.path.remove(_ROOT)

    calls = []

    def fake_run(argv, capture_output, text, timeout):
        calls.append(argv)
        return types.SimpleNamespace(returncode=1, stdout="", stderr="boom")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.delenv("HOROVOD_BENCH_PREFLIGHT", raising=False)
    monkeypatch.setenv("HOROVOD_BENCH_PREFLIGHT_ATTEMPTS", "2")
    assert bench._preflight_backend(fatal=False) is None
    assert len(calls) == 2
