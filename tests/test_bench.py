"""bench.py is the driver's perf artifact — it must always run end to end.

Round-1 postmortem: the bench had never executed before the driver ran it,
and it died inside ``hvd.init()`` with zero measured numbers. This test
executes the REAL bench script (tiny sizes, platform pinned to CPU, the
preflight skipped via its documented knob) and asserts the machine-readable
result line, so any refactor that breaks the artifact fails CI instead of
the round.
"""

import json
import os
import subprocess
import sys

import pytest

# Subprocess/soak-heavy by design: excluded from the quick tier (-m "not soak").
pytestmark = pytest.mark.soak

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_end_to_end_cpu(tmp_path):
    """One CPU run covers the whole artifact: the result line (including
    the MFU additions — achieved TFLOP/s from the compiled module's cost
    analysis; mfu_pct only appears on real accelerators) and the
    HOROVOD_BENCH_DUMP_HLO audit dump, so the multi-minute AOT compile is
    paid once."""
    hlo_path = str(tmp_path / "step_hlo.txt")
    bootstrap = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys, runpy; "
        "sys.argv = ['bench.py', '--batch-size', '2', "
        "'--num-warmup-batches', '1', '--num-batches-per-iter', '1', "
        "'--num-iters', '1']; "
        f"runpy.run_path({os.path.join(_ROOT, 'bench.py')!r}, "
        "run_name='__main__')"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"HOROVOD_BENCH_PREFLIGHT": "0",
                "HOROVOD_BENCH_DUMP_HLO": hlo_path})
    result = subprocess.run(
        [sys.executable, "-c", bootstrap], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"bench.py failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["metric"] == \
        "resnet50_synthetic_train_images_per_sec_per_device"
    assert line["value"] > 0
    assert line["unit"] == "img/s"
    assert isinstance(line["vs_baseline"], float)
    assert line["tflops_per_device"] > 0
    assert "mfu_pct" not in line  # meaningless on CPU, by design
    with open(hlo_path) as f:
        hlo = f.read()
    assert "ENTRY" in hlo or "HloModule" in hlo


def test_onchip_path_bench_cpu():
    """The single-device residency bench (docs/benchmarks.md) must run and
    produce its comparison row."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["HOROVOD_BENCH_PLATFORM"] = "cpu"
    result = subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "benchmarks", "onchip_path_bench.py"),
         "--tensors", "8", "--elems", "1024", "--rounds", "3"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, result.stderr
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["host_tensors_per_s"] > 0
    assert line["onchip_tensors_per_s"] > 0


def test_bench_supervised_path_cpu():
    """The driver-facing path: supervisor parent + measurement child.

    Round-2 postmortem: the tunnel wedged AFTER a clean preflight, inside
    the first compile — so the measurement itself must run in a killable,
    retryable child. This exercises that exact topology on CPU (preflight
    skipped, supervision forced on, child pinned via
    HOROVOD_BENCH_PLATFORM) and asserts the JSON line is relayed through
    the parent."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"HOROVOD_BENCH_PREFLIGHT": "0",
                "HOROVOD_BENCH_SUPERVISE": "1",
                "HOROVOD_BENCH_PLATFORM": "cpu"})
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--batch-size", "2", "--num-warmup-batches", "1",
         "--num-batches-per-iter", "1", "--num-iters", "1"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"bench.py supervised failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    assert "[supervise 1/" in result.stderr
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["value"] > 0


def test_bench_watcher_env_skips_initial_preflight_cpu():
    """The chip watcher's exact env: preflight ON (so the supervisor's
    inter-attempt backend wait stays armed) but the INITIAL preflight
    skipped (HOROVOD_BENCH_PREFLIGHT_INITIAL=0) because the watcher's own
    compute probe ran seconds earlier — one fewer backend spin-up inside
    a short healthy window. Asserts supervision still runs and no initial
    preflight probe line precedes it."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"HOROVOD_BENCH_PREFLIGHT_INITIAL": "0",
                "HOROVOD_BENCH_PLATFORM": "cpu"})
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--batch-size", "2", "--num-warmup-batches", "1",
         "--num-batches-per-iter", "1", "--num-iters", "1"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"bench.py failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    assert "[supervise 1/" in result.stderr
    pre_supervise = result.stderr.split("[supervise 1/")[0]
    assert "[preflight" not in pre_supervise
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["value"] > 0


def _write_capture(path, **overrides):
    rec = {"metric": "resnet50_synthetic_train_images_per_sec_per_device",
           "value": 1699.5, "unit": "img/s", "vs_baseline": 16.412,
           "live": True, "batch_size": 32, "n_devices": 1,
           "captured_at": 1700000000.0}
    rec.update(overrides)
    path.write_text(json.dumps(rec) + "\n")


def test_wedge_fallback_emits_latest_real_capture(tmp_path):
    """Rounds 1-3 postmortem: the driver's end-of-round run always hit a
    wedged tunnel and recorded rc=1 even when a real number had been
    measured mid-round. When live measurement is impossible, bench.py must
    emit the newest watcher-captured REAL measurement for the requested
    config, provenance-marked — and never a mismatched config, nor a
    previous fallback line (no chaining)."""
    out = tmp_path / "bench_results_rX"
    out.mkdir()
    _write_capture(out / "old.json", value=100.0, captured_at=1.0)
    _write_capture(out / "newest.json", value=1720.0, captured_at=9e9)
    # decoys: wrong batch size, wrong model, and an earlier fallback line
    _write_capture(out / "bs128.json", batch_size=128, captured_at=9.5e9)
    _write_capture(out / "vgg.json", captured_at=9.5e9,
                   metric="vgg16_synthetic_train_images_per_sec_per_device")
    _write_capture(out / "fb.json", live=False, captured_at=9.5e9)
    env = dict(os.environ)
    env.update({
        # an unknown platform makes the probe fail fast instead of hanging
        "JAX_PLATFORMS": "nonexistent_backend",
        "HOROVOD_BENCH_PROBE_TIMEOUT_S": "10",
        "HOROVOD_BENCH_PREFLIGHT_ATTEMPTS": "1",
        "HOROVOD_BENCH_FALLBACK_GLOB": str(out / "*.json"),
    })
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, (
        f"fallback path failed\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    line = json.loads(result.stdout.strip().splitlines()[-1])
    assert line["value"] == 1720.0
    assert line["live"] is False
    assert line["captured_by"] == "chip_watch"
    assert line["captured_at"] == 9e9
    assert line["captured_from"].endswith("newest.json")


def test_fallback_prefers_revision_matched_capture(tmp_path):
    """Round-4 advisor: the 24h freshness bound alone can emit a number
    measured on older code within the same round. A capture stamped with
    the current HEAD sha must beat a NEWER capture from another revision;
    when only a mismatched-revision capture exists it is still emitted
    (a real number beats rc=1) but flagged revision_match=false."""
    head = subprocess.run(
        ["git", "-C", _ROOT, "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True).stdout.strip()
    assert head

    def run_with(captures):
        out = tmp_path / "revs"
        if out.exists():
            import shutil
            shutil.rmtree(out)
        out.mkdir()
        for name, overrides in captures.items():
            _write_capture(out / name, **overrides)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "nonexistent_backend",
            "HOROVOD_BENCH_PROBE_TIMEOUT_S": "10",
            "HOROVOD_BENCH_PREFLIGHT_ATTEMPTS": "1",
            "HOROVOD_BENCH_FALLBACK_GLOB": str(out / "*.json"),
        })
        result = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "bench.py")],
            cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
        assert result.returncode == 0, result.stderr
        return json.loads(result.stdout.strip().splitlines()[-1]), result

    # current-revision capture wins over a newer foreign-revision one
    rec, _ = run_with({
        "old_rev.json": dict(value=999.0, captured_at=9.5e9,
                             git_sha="0000000"),
        "cur_rev.json": dict(value=1720.0, captured_at=9e9, git_sha=head),
    })
    assert rec["value"] == 1720.0
    assert rec["revision_match"] is True

    # only a mismatched capture: emitted, flagged, and logged
    rec, result = run_with({
        "old_rev.json": dict(value=999.0, captured_at=9.5e9,
                             git_sha="0000000"),
    })
    assert rec["value"] == 999.0
    assert rec["revision_match"] is False
    assert "measured on revision" in result.stderr


def test_wedge_fallback_disabled_or_empty_stays_red(tmp_path):
    """With no matching capture (or HOROVOD_BENCH_FALLBACK=0 even when a
    matching capture exists — the watcher's own mode, so it can never
    satisfy itself from old data) a wedged run must still exit nonzero —
    the fallback may only ever substitute a real measurement, never invent
    success."""
    empty = tmp_path / "empty"
    empty.mkdir()
    stocked = tmp_path / "stocked"
    stocked.mkdir()
    _write_capture(stocked / "resnet50.json", captured_at=9e9)
    for glob_dir, extra_env, want_no_match_log in (
            (empty, {}, True),
            (stocked, {"HOROVOD_BENCH_FALLBACK": "0"}, False)):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "nonexistent_backend",
            "HOROVOD_BENCH_PROBE_TIMEOUT_S": "10",
            "HOROVOD_BENCH_PREFLIGHT_ATTEMPTS": "1",
            "HOROVOD_BENCH_FALLBACK_GLOB": str(glob_dir / "*.json"),
        })
        env.update(extra_env)
        result = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "bench.py")],
            cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
        assert result.returncode == 1, (glob_dir, result.stderr)
        assert result.stdout.strip() == "", (glob_dir, result.stdout)
        no_match = "[fallback] no previously captured measurement" \
            in result.stderr
        # empty dir: the scan ran and found nothing; FALLBACK=0 with a
        # matching capture present: the scan must never run at all
        assert no_match == want_no_match_log, (glob_dir, result.stderr)


def test_stale_fallback_capture_is_ignored(tmp_path):
    """A capture older than HOROVOD_BENCH_FALLBACK_MAX_AGE_S (default 24h)
    measured a different tree; it must not keep the scoreboard green."""
    out = tmp_path / "stale"
    out.mkdir()
    _write_capture(out / "resnet50.json", captured_at=1700000000.0)  # 2023
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "nonexistent_backend",
        "HOROVOD_BENCH_PROBE_TIMEOUT_S": "10",
        "HOROVOD_BENCH_PREFLIGHT_ATTEMPTS": "1",
        "HOROVOD_BENCH_FALLBACK_GLOB": str(out / "*.json"),
    })
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 1
    assert result.stdout.strip() == ""


def test_no_fallback_when_measurement_child_crashes(tmp_path):
    """A child that FAILS fast (rc != 0, never hanging) is a code
    regression, not a wedge — the supervisor must not mask it with a stale
    capture (bench would rot green)."""
    out = tmp_path / "stocked"
    out.mkdir()
    _write_capture(out / "resnet50.json", captured_at=9e9)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "HOROVOD_BENCH_PREFLIGHT": "0",
        "HOROVOD_BENCH_SUPERVISE": "1",
        "HOROVOD_BENCH_MEASURE_ATTEMPTS": "1",
        # the child dies at backend init: a fast failure, not a hang
        "HOROVOD_BENCH_PLATFORM": "nonexistent_backend",
        "HOROVOD_BENCH_FALLBACK_GLOB": str(out / "*.json"),
    })
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 1, result.stderr
    assert result.stdout.strip() == ""
    assert "not a chip wedge" in result.stderr


def test_preflight_nonfatal_returns_none(monkeypatch):
    """The supervisor's inter-attempt probe (after SIGKILLing a hung
    child, the tunnel lease can take a while to clear) must NOT exit the
    process when the backend stays down — the last measurement attempt
    still deserves its chance. Probes are mocked: this test must never
    touch a real accelerator."""
    import types

    sys.path.insert(0, _ROOT)
    try:
        import bench
    finally:
        sys.path.remove(_ROOT)

    calls = []

    def fake_run(argv, capture_output, text, timeout):
        calls.append(argv)
        return types.SimpleNamespace(returncode=1, stdout="", stderr="boom")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.delenv("HOROVOD_BENCH_PREFLIGHT", raising=False)
    monkeypatch.setenv("HOROVOD_BENCH_PREFLIGHT_ATTEMPTS", "2")
    assert bench._preflight_backend(fatal=False) is None
    assert len(calls) == 2


def test_preflight_hang_fails_fast(monkeypatch):
    """A probe that HANGS (TimeoutExpired) means a wedged accelerator, not
    a transient failure: the preflight must stop after the FIRST hang
    instead of burning attempts x probe-timeout on identical hangs (the
    round-5 bench log lost ~8 min to 4 x 120 s of them before reaching the
    fallback line). Transient NON-ZERO exits keep the full retry budget —
    pinned by test_preflight_nonfatal_returns_none above."""
    import types  # noqa: F401 - parity with the sibling test's imports

    sys.path.insert(0, _ROOT)
    try:
        import bench
    finally:
        sys.path.remove(_ROOT)

    calls = []

    def fake_run(argv, capture_output, text, timeout):
        calls.append(argv)
        raise bench.subprocess.TimeoutExpired(argv, timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.delenv("HOROVOD_BENCH_PREFLIGHT", raising=False)
    monkeypatch.setenv("HOROVOD_BENCH_PREFLIGHT_ATTEMPTS", "4")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_TIMEOUT_S", "10")
    assert bench._preflight_backend(fatal=False) is None
    assert len(calls) == 1  # one hang, zero identical retries


def test_lm_bench_end_to_end_cpu():
    """The Transformer-LM benchmark (second flagship workload) must run
    end to end on CPU for both attention backends and emit the JSON line
    — the watcher drives the same script on TPU."""
    for attention in ("dense", "flash"):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["HOROVOD_BENCH_PLATFORM"] = "cpu"
        result = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "benchmarks",
                                          "lm_bench.py"),
             "--num-layers", "1", "--num-heads", "2", "--d-model", "32",
             "--d-ff", "64", "--vocab-size", "128", "--seq-len", "128",
             "--batch-size", "1", "--num-warmup-batches", "1",
             "--num-batches-per-iter", "1", "--num-iters", "1",
             "--attention", attention],
            cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
        assert result.returncode == 0, (attention, result.stderr)
        line = json.loads(result.stdout.strip().splitlines()[-1])
        assert line["metric"] == "transformer_lm_tokens_per_sec_per_device"
        assert line["value"] > 0
        assert line["attention"] == attention
        assert line["tflops_per_device"] > 0


def test_scan_mode_marked_and_excluded_from_fallback(tmp_path):
    """HOROVOD_BENCH_SCAN_BATCHES runs are a diagnostic (one lax.scan-ned
    device call per iteration), NOT the reference protocol: the result
    line must carry scan_batches, and the wedge fallback must never
    substitute such a capture for a protocol run."""
    out = tmp_path / "caps"
    out.mkdir()
    _write_capture(out / "scan.json", value=9999.0, captured_at=9e9,
                   scan_batches=10)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "nonexistent_backend",
        "HOROVOD_BENCH_PROBE_TIMEOUT_S": "10",
        "HOROVOD_BENCH_PREFLIGHT_ATTEMPTS": "1",
        "HOROVOD_BENCH_FALLBACK_GLOB": str(out / "*.json"),
    })
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=560)
    assert result.returncode == 1  # scan capture must not satisfy protocol
    assert result.stdout.strip() == ""

    # and the scan wrapper itself: N scanned batches == N separate steps
    # (tiny model in-process; a full bench.py scan run costs minutes of
    # ResNet-50 compile and belongs on the chip, not in CI)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    import horovod_tpu as hvd
    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import ResNet
    from horovod_tpu.models.resnet import ResNetBlock

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10,
                   block_cls=ResNetBlock, dtype=jnp.float32)
    x = jnp.ones((8, 16, 16, 3), jnp.float32)
    y = jnp.zeros((8,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name="data")
    opt_state = opt.init(params)

    single = make_dp_train_step(model, opt, mesh, donate=False)
    scanned = make_dp_train_step(model, opt, mesh, donate=False,
                                 scan_batches=3)
    p1, s1, b1 = params, opt_state, batch_stats
    for _ in range(3):
        p1, s1, b1 = single(p1, s1, b1, x, y)
    p3, s3, b3 = scanned(params, opt_state, batch_stats, x, y)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), p1, p3)
