"""Eager named-tensor collectives, size-1 world.

Mirrors the reference correctness pattern: seeded random tensor →
collective → compare against expectation over dtype x dim sweeps
(``test/test_torch.py:73-108``), async fused submissions
(``test_torch.py:180``), duplicate-name rejection (``test_torch.py:356``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

DTYPES = [np.float32, np.float64, np.int32, np.int64, np.float16,
          np.uint8, np.int8, np.uint16, np.int16]
DIMS = [1, 2, 3]


def test_allreduce_dtypes_dims(hvd):
    """Reference-style dtype x dim sweep (``test_torch.py:73-108``); every
    wire dtype of ``messages.DataType`` except bool/bf16 (covered below)."""
    rng = np.random.default_rng(1234)
    for dtype in DTYPES:
        for dim in DIMS:
            x = rng.uniform(0, 100, size=(17,) * dim).astype(dtype)
            out = hvd.allreduce(x, average=False, name=f"ar_{dtype.__name__}_{dim}")
            assert np.asarray(out).dtype == dtype
            np.testing.assert_array_equal(np.asarray(out), x)  # size-1 sum


def test_allgather_dtypes(hvd):
    rng = np.random.default_rng(99)
    for dtype in DTYPES + [np.bool_]:
        x = (rng.uniform(0, 2, size=(3, 2)) > 1).astype(dtype) \
            if dtype == np.bool_ else \
            rng.uniform(0, 50, size=(3, 2)).astype(dtype)
        out = hvd.allgather(x, name=f"ag_{np.dtype(dtype).name}")
        np.testing.assert_array_equal(np.asarray(out), x)


def test_allreduce_average(hvd):
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = hvd.allreduce(x, average=True)
    np.testing.assert_allclose(np.asarray(out), x)


def test_allreduce_jax_array_roundtrip(hvd):
    x = jnp.arange(8, dtype=jnp.float32)
    out = hvd.allreduce(x, average=True)
    assert isinstance(out, type(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_allreduce_jax_device_resident_no_alias(hvd):
    """World-of-one device path: jax in → jax out with no host staging,
    and the result must be a copy — a caller later donating its input
    buffer to a jit must not invalidate the allreduce result."""
    import jax

    x = jnp.arange(16, dtype=jnp.float32)
    out = hvd.allreduce(x, average=False, name="dev_res")
    assert isinstance(out, jax.Array)
    assert out is not x
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_allreduce_async_survives_input_deletion(hvd):
    """The submission must be an on-device snapshot: a caller deleting (or
    jit-donating) its buffer between allreduce_async and the fusion cycle
    must not fail the collective — nor poison other tensors fused into the
    same batch."""
    import jax

    x = jnp.arange(1024, dtype=jnp.float32)
    y = jnp.ones(1024, dtype=jnp.float32)
    hx = hvd.allreduce_async(x, average=False, name="donated")
    hy = hvd.allreduce_async(y, average=False, name="survivor")
    x.delete()  # what jit(donate_argnums=...) does to the buffer
    out = hvd.synchronize(hx)
    assert isinstance(out, jax.Array)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(1024, dtype=np.float32))
    np.testing.assert_array_equal(np.asarray(hvd.synchronize(hy)), 1.0)


def test_allreduce_async_fused_jax(hvd):
    """A burst of device-array submissions rides one fusion cycle and every
    result comes back as a device array (the on-chip fused path)."""
    import jax

    handles = [hvd.allreduce_async(jnp.full((32,), float(i)), average=False,
                                   name=f"jaxfused.{i}") for i in range(6)]
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        assert isinstance(out, jax.Array)
        np.testing.assert_array_equal(np.asarray(out), float(i))


def test_allgather_broadcast_jax_device_resident(hvd):
    """Size-1 device path for the movement ops: jax in → jax out, values
    intact, dtypes preserved."""
    import jax

    g = hvd.allgather(jnp.arange(6, dtype=jnp.int32).reshape(3, 2),
                      name="dev_gather")
    assert isinstance(g, jax.Array)
    np.testing.assert_array_equal(np.asarray(g),
                                  np.arange(6, dtype=np.int32).reshape(3, 2))
    b = hvd.broadcast(jnp.arange(4, dtype=jnp.int8), root_rank=0,
                      name="dev_bcast")
    assert isinstance(b, jax.Array)
    assert np.asarray(b).dtype == np.int8
    np.testing.assert_array_equal(np.asarray(b), np.arange(4, dtype=np.int8))


def test_allreduce_bfloat16(hvd):
    x = jnp.ones((4, 4), dtype=jnp.bfloat16)
    out = hvd.allreduce(x, average=False)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out, dtype=np.float32), 1.0)


def test_allreduce_async_fused(hvd):
    """Many tensors in flight at once forces the fusion path
    (``test_horovod_allreduce_async_fused``)."""
    rng = np.random.default_rng(42)
    tensors = [rng.standard_normal((50, 50)).astype(np.float32)
               for _ in range(20)]
    handles = [hvd.allreduce_async(t, average=False, name=f"fused_{i}")
               for i, t in enumerate(tensors)]
    for t, h in zip(tensors, handles):
        np.testing.assert_array_equal(np.asarray(hvd.synchronize(h)), t)


def test_poll(hvd):
    x = np.ones(4, dtype=np.float32)
    h = hvd.allreduce_async(x, name="pollme")
    hvd.synchronize(h) is not None  # noqa: B015 - wait first
    # After synchronize, handle is consumed; poll on fresh handle:
    h2 = hvd.allreduce_async(x, name="pollme2")
    import time
    deadline = time.time() + 5
    while not hvd.poll(h2) and time.time() < deadline:
        time.sleep(0.001)
    assert hvd.poll(h2)
    hvd.synchronize(h2)


def test_duplicate_name_rejected(hvd):
    x = np.ones(1000_000, dtype=np.float32)
    h = hvd.allreduce_async(x, name="dup")
    with pytest.raises(ValueError, match="same name"):
        hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h)


def test_allgather_identity(hvd):
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = hvd.allgather(x)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_broadcast_identity_and_bad_root(hvd):
    x = np.arange(4, dtype=np.int32)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out), x)
    with pytest.raises(hvd.HorovodInternalError, match="root rank"):
        hvd.broadcast(x, root_rank=3, name="bad_root")


def test_compression_fp16(hvd):
    x = np.linspace(-1, 1, 256, dtype=np.float32)
    out = hvd.allreduce(x, average=False, compression=hvd.Compression.fp16,
                        name="comp16")
    assert np.asarray(out).dtype == np.float32
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-3)


def test_compression_bf16(hvd):
    x = jnp.linspace(-1, 1, 256, dtype=jnp.float32)
    out = hvd.allreduce(x, average=False, compression=hvd.Compression.bf16,
                        name="compbf16")
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-2)


def test_shutdown_errors_outstanding_after_stop(hvd):
    # enqueue then immediately shut down: handle must resolve (possibly OK if
    # the cycle ran first, else SHUT_DOWN_ERROR) — never hang.
    x = np.ones(4, dtype=np.float32)
    h = hvd.allreduce_async(x, name="shutdown_race")
    hvd.shutdown()
    hvd.init()


def test_handle_eviction_tombstones():
    """Past MAX_RETAINED unclaimed results the payload is dropped, but a
    late waiter must get a self-explanatory eviction error — never
    'unknown handle' for a handle it could still legitimately claim
    (round-3 verdict weakness #5). Unit-level: drives HandleManager
    directly with a tiny threshold."""
    from horovod_tpu.core.status import Status
    from horovod_tpu.ops.engine import HandleManager

    hm = HandleManager()
    victim = hm.allocate()
    hm.mark_done(victim, Status.ok(), np.float32(1.0))
    assert hm.poll(victim)

    old_retained, old_tomb = hm.MAX_RETAINED, hm.MAX_TOMBSTONES
    hm.MAX_RETAINED, hm.MAX_TOMBSTONES = 4, 16
    try:
        for _ in range(8):
            h = hm.allocate()
            hm.mark_done(h, Status.ok(), np.float32(2.0))
        # victim's payload was evicted, but poll still answers and wait
        # explains the eviction instead of claiming the handle is unknown
        assert hm.poll(victim)
        with pytest.raises(ValueError, match="evicted"):
            hm.wait(victim)
        # fresh handles still round-trip
        assert float(hm.wait(h)) == 2.0
        # past MAX_TOMBSTONES even the tombstone goes: unknown handle is
        # then accurate
        first = hm.allocate()
        hm.mark_done(first, Status.ok(), None)
        for _ in range(hm.MAX_TOMBSTONES + hm.MAX_RETAINED + 1):
            h2 = hm.allocate()
            hm.mark_done(h2, Status.ok(), None)
        with pytest.raises(ValueError, match="unknown handle"):
            hm.wait(first)
    finally:
        hm.MAX_RETAINED, hm.MAX_TOMBSTONES = old_retained, old_tomb


def test_default_secret_warns_once(monkeypatch):
    """The fixed development HMAC key must announce itself (round-3 verdict
    weakness #4): any local process can speak to a controller keyed with
    it. The launcher path (HOROVOD_SECRET_KEY set) stays silent."""
    import warnings

    from horovod_tpu.runner import network

    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    monkeypatch.setattr(network, "_warned_default_secret", False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        network.default_secret()
        network.default_secret()  # once per process, not per call
    hits = [w for w in caught if "HOROVOD_SECRET_KEY" in str(w.message)]
    assert len(hits) == 1

    monkeypatch.setenv("HOROVOD_SECRET_KEY", network.make_secret())
    monkeypatch.setattr(network, "_warned_default_secret", False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        network.default_secret()
    assert not [w for w in caught if "HOROVOD_SECRET_KEY" in str(w.message)]


def test_size1_explicit_xla_plane(monkeypatch):
    """HOROVOD_DATA_PLANE=xla in a world of one must still build the device
    plane and route host allreduce buffers through it (H2D -> compiled
    reduce -> D2H) — the measured single-chip path for the eager
    front-ends (round-4 verdict weak #5). "auto" keeps the pure-host
    short-circuit: no plane, same numbers."""
    import horovod_tpu as hvd_mod
    from horovod_tpu.ops.engine import get_engine

    for plane_env, expect_plane in (("xla", True), ("auto", False)):
        monkeypatch.setenv("HOROVOD_DATA_PLANE", plane_env)
        hvd_mod.init()
        try:
            out = hvd_mod.allreduce(np.full((2048,), 2.0, np.float32),
                                    average=False)
            np.testing.assert_array_equal(np.asarray(out), 2.0)
            engine = get_engine()
            assert (engine._plane is not None) == expect_plane, plane_env
        finally:
            hvd_mod.shutdown()


def test_size1_xla_plane_guarded_in_foreign_worlds(monkeypatch):
    """The explicit size-1 device plane must NOT build when the size-1
    world does not own the JAX process world — a subset non-member or a
    pod-wide HOROVOD_DATA_PLANE=xla export would otherwise crash init on
    XlaDataPlane's one-process-per-rank requirement. It is skipped with a
    warning and collectives short-circuit on host."""
    import logging

    import horovod_tpu as hvd_mod
    from horovod_tpu.core.logging import LOG
    from horovod_tpu.ops import engine as engine_mod

    class Capture(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.WARNING)
            self.messages = []

        def emit(self, record):
            self.messages.append(record.getMessage())

    monkeypatch.setenv("HOROVOD_DATA_PLANE", "xla")
    # simulate a multi-process JAX world around this size-1 engine
    monkeypatch.setattr(engine_mod, "_jax_multiprocess", lambda: True)
    cap = Capture()
    LOG.addHandler(cap)
    try:
        # inside the try: a guard regression makes init() itself raise,
        # and the handler/world must still be cleaned up
        hvd_mod.init()
        out = hvd_mod.allreduce(np.full((64,), 3.0, np.float32),
                                average=False)
        np.testing.assert_array_equal(np.asarray(out), 3.0)
        assert engine_mod.get_engine()._plane is None
    finally:
        LOG.removeHandler(cap)
        hvd_mod.shutdown()
    assert any("ignored for this size-1 world" in m for m in cap.messages)
