"""Thread soak: several API threads submit concurrently on every rank.

The reference's eager path receives submissions from framework hook
threads in arbitrary interleavings; the coordinator tolerates runtime
reorder because names, not order, drive negotiation. Each thread owns a
disjoint name space with the same rng stream on every rank, so all
ranks submit the same global set in different per-rank interleavings —
correctness-checked end to end."""
import os
import sys
import threading

os.environ.pop("JAX_PLATFORMS", None)
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import horovod_tpu as hvd

# COUNT-based, not time-based: ranks must submit identical sets, and a
# wall-clock budget lets a fast rank finish + shutdown while a slow rank
# still submits - correctly yielding the reference's SHUT_DOWN_ERROR,
# which is not what this soak measures.
CYCLES = int(os.environ.get("SOAK_CYCLES", "150"))
N_THREADS = 3
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
hvd.init()
errors = []


def submitter(tid: int) -> None:
    try:
        rng = np.random.default_rng(1000 + tid)  # same per tid on all ranks
        for cyc in range(CYCLES):
            checks = []
            for i in range(int(rng.integers(1, 6))):
                shape = (int(rng.integers(1, 128)),)
                name = f"tsoak.{tid}.{cyc}.{i}"
                base = np.arange(shape[0], dtype=np.float32)
                kind = int(rng.integers(0, 2))
                if kind == 0:
                    h = hvd.allreduce_async(base + rank, average=False,
                                            name=name)
                    checks.append((h, base * size + sum(range(size))))
                else:
                    root = int(rng.integers(0, size))
                    h = hvd.broadcast_async(base + rank * 5, root_rank=root,
                                            name=name)
                    checks.append((h, base + root * 5))
            for h, want in checks:
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), want, rtol=1e-6)
    except Exception as exc:  # noqa: BLE001 - surface via main thread
        errors.append(exc)


threads = [threading.Thread(target=submitter, args=(t,))
           for t in range(N_THREADS)]
for t in threads:
    t.start()
for t in threads:
    t.join()
hvd.shutdown()
if errors:
    raise errors[0]
print(f"TSOAK-OK rank {rank}", flush=True)
os._exit(0)
