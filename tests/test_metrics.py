"""Observability plane tests (docs/metrics.md).

Coverage, per the acceptance criteria: registry hot-path cost (perf
smoke, not a bench gate), world merge exactness (histogram bucket sums
equal the per-rank sums), Prometheus exposition + the shared format-lint
helper, exposition strictly absent when ``HOROVOD_METRICS_PORT`` is
unset, the wire/negotiation counter migration (read-through back-compat
properties, thread-safe increments), the registry→timeline bridge, and
2-process acceptance: world aggregation over the control wire, and
bit-exact training results with metrics on (plus a chaos-injected
reconnect and a mid-run world-snapshot pull) vs everything off.
"""

import gc
import hashlib
import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.obs.bridge import TimelineBridge
from horovod_tpu.obs.exposition import (
    MetricsServer,
    parse_prometheus,
    render_prometheus,
)
from horovod_tpu.obs.registry import (
    Counter,
    Registry,
    merge_snapshots,
    registry as global_registry,
)

SECRET = b"s" * 32


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- registry unit ------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("horovod_c_total", "help text")
    c.inc()
    c.inc(41)
    assert c.value == 42
    g = reg.gauge("horovod_g")
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value == 9
    h = reg.histogram("horovod_h_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = reg.snapshot()["horovod_h_seconds"]["samples"][0]
    assert snap["buckets"] == [1, 1, 1, 1]  # one per bucket + one in +Inf
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(5.555)


def test_get_or_create_and_type_conflicts():
    reg = Registry()
    a = reg.counter("horovod_x_total")
    assert reg.counter("horovod_x_total") is a  # get-or-create
    with pytest.raises(ValueError):
        reg.gauge("horovod_x_total")  # type conflict fails loudly
    with pytest.raises(ValueError):
        reg.counter("horovod_x_total", labels=("kind",))  # label conflict


def test_labeled_families():
    reg = Registry()
    fam = reg.counter("horovod_faults_total", labels=("kind",))
    fam.labels(kind="drop").inc()
    fam.labels(kind="drop").inc()
    fam.labels(kind="delay").inc()
    with pytest.raises(ValueError):
        fam.inc()  # labeled family has no default child
    with pytest.raises(ValueError):
        fam.labels(wrong="x")
    snap = reg.snapshot()["horovod_faults_total"]
    by_kind = {s["labels"]["kind"]: s["value"] for s in snap["samples"]}
    assert by_kind == {"drop": 2, "delay": 1}


def test_histogram_world_merge_is_pointwise():
    """The aggregation contract: a world merge is an exact bucket-wise
    sum (fixed bounds, no re-binning)."""
    regs = [Registry() for _ in range(3)]
    for i, reg in enumerate(regs):
        h = reg.histogram("horovod_h_seconds", buckets=(0.01, 0.1))
        for v in [0.001 * (i + 1), 0.05, 2.0][:i + 1]:
            h.observe(v)
        reg.counter("horovod_c_total").inc(i + 1)
    for reg in regs:
        reg.gauge("horovod_world_size").set(3)
    snaps = [r.snapshot() for r in regs]
    merged = merge_snapshots(snaps)
    m = merged["horovod_h_seconds"]["samples"][0]
    per_rank = [s["horovod_h_seconds"]["samples"][0] for s in snaps]
    assert m["buckets"] == [sum(col) for col in
                            zip(*[p["buckets"] for p in per_rank])]
    assert m["count"] == sum(p["count"] for p in per_rank)
    assert m["sum"] == pytest.approx(sum(p["sum"] for p in per_rank))
    assert merged["horovod_c_total"]["samples"][0]["value"] == 6
    # gauges merge by max, not sum: identity values must survive the fold
    assert merged["horovod_world_size"]["samples"][0]["value"] == 3


def test_merge_rejects_mismatched_bounds():
    r1, r2 = Registry(), Registry()
    r1.histogram("horovod_h_seconds", buckets=(0.01,)).observe(1.0)
    r2.histogram("horovod_h_seconds", buckets=(0.5,)).observe(1.0)
    with pytest.raises(ValueError):
        merge_snapshots([r1.snapshot(), r2.snapshot()])


def test_counter_hot_path_perf_smoke():
    """The acceptance claim: registry ops are O(1) and allocation-free on
    the counter hot path. Perf smoke, not a bench gate — the time bound
    is an order of magnitude above the measured cost, and the allocation
    check counts gc-tracked objects (ints are untracked, so any per-inc
    container churn would show)."""
    reg = Registry()
    fam = reg.counter("horovod_perf_total")
    child = fam.labels() if fam.label_names else fam
    child.inc()  # warm
    gc.collect()
    before = len(gc.get_objects())
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        child.inc(3)
    per_op = (time.perf_counter() - t0) / n
    gc.collect()
    after = len(gc.get_objects())
    assert per_op < 20e-6, f"{per_op * 1e6:.2f} us per inc"
    assert after - before < 20, "counter inc allocates gc-tracked objects"
    assert child.value == 3 * n + 1


def test_counter_increments_safe_across_threads():
    c = Counter()
    n, threads = 5000, 8

    def worker() -> None:
        for _ in range(n):
            c.inc()

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == n * threads  # a bare += would undercount here


# -- Prometheus rendering / format lint ---------------------------------------

def test_render_parse_roundtrip():
    reg = Registry()
    reg.counter("horovod_c_total", "a counter").inc(3)
    reg.gauge("horovod_g", "a gauge").set(-1.5)
    h = reg.histogram("horovod_h_seconds", "a hist", buckets=(0.01, 1.0))
    h.observe(0.5)
    lab = reg.counter("horovod_l_total", labels=("path",))
    lab.labels(path="host").inc()
    text = render_prometheus(reg.snapshot())
    types = parse_prometheus(text)  # the shared lint helper: raises on rot
    assert types == {"horovod_c_total": "counter", "horovod_g": "gauge",
                     "horovod_h_seconds": "histogram",
                     "horovod_l_total": "counter"}
    assert 'horovod_l_total{path="host"} 1' in text
    assert 'horovod_h_seconds_bucket{le="+Inf"} 1' in text


@pytest.mark.parametrize("bad", [
    "horovod_undeclared 1",                      # sample without TYPE
    "# TYPE horovod_x summary",                  # unknown type
    '# TYPE horovod_x counter\nhorovod_x{a=} 1',  # malformed label
    "# TYPE horovod_x counter\nhorovod_x one",   # non-numeric value
])
def test_lint_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_prometheus(bad + "\n")


def test_lint_rejects_non_cumulative_histogram():
    text = ("# TYPE horovod_h histogram\n"
            'horovod_h_bucket{le="0.1"} 5\n'
            'horovod_h_bucket{le="1"} 3\n'  # decreasing: not cumulative
            'horovod_h_bucket{le="+Inf"} 5\n'
            "horovod_h_sum 1\nhorovod_h_count 5\n")
    with pytest.raises(ValueError):
        parse_prometheus(text)


# -- HTTP exposition ----------------------------------------------------------

def test_http_server_serves_both_endpoints():
    reg = Registry()
    reg.counter("horovod_c_total").inc(9)

    def provider():
        local = reg.snapshot()
        return {"world": merge_snapshots([local]), "ranks": {0: local}}

    server = MetricsServer(0, provider)  # ephemeral test port
    try:
        base = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        assert "horovod_c_total 9" in text
        parse_prometheus(text)
        doc = json.loads(urllib.request.urlopen(
            base + "/metrics.json", timeout=10).read().decode())
        assert doc["world"]["horovod_c_total"]["samples"][0]["value"] == 9
        assert "0" in doc["ranks"] or 0 in doc["ranks"]
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=10)
    finally:
        server.close()
    from horovod_tpu.obs import exposition

    assert exposition.metrics_port() is None or \
        exposition.metrics_port() != server.port


def test_exposition_absent_when_port_unset(monkeypatch):
    """The acceptance criterion: no HOROVOD_METRICS_PORT means no server,
    no thread, no socket."""
    monkeypatch.delenv("HOROVOD_METRICS_PORT", raising=False)
    import horovod_tpu as hvd

    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        assert hvd.obs.metrics_port() is None
        assert not [t for t in threading.enumerate()
                    if t.name == "horovod-metrics-http"]
    finally:
        hvd.shutdown()


def test_exposition_serves_and_stops_with_world(monkeypatch):
    port = _free_port()
    monkeypatch.setenv("HOROVOD_METRICS_PORT", str(port))
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    try:
        assert hvd.obs.metrics_port() == port
        hvd.allreduce(np.ones((4,), np.float32), name="obs.expo")
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        types = parse_prometheus(text)
        assert "horovod_world_size" in types
    finally:
        hvd.shutdown()
    assert hvd.obs.metrics_port() is None  # closed with the world
    with pytest.raises(OSError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                               timeout=2)


def test_metrics_snapshot_local_and_world_single_process():
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    try:
        hvd.allreduce(np.ones((4,), np.float32), name="obs.snap")
        local = hvd.metrics_snapshot()
        assert "horovod_world_size" in local
        world = hvd.metrics_snapshot(world=True)
        assert set(world) == {"world", "ranks"}
        assert list(world["ranks"]) == [0]  # size-1: this rank alone
    finally:
        hvd.shutdown()


# -- wire / negotiation counter migration -------------------------------------

class _NullSock:
    def sendall(self, data) -> None:
        pass


def test_wire_tx_counter_threadsafe_and_readthrough():
    """The migration satellite: Wire.tx_bytes is a read-through property
    over a registry Counter, and concurrent writers on a SHARED wire (the
    service's handler threads) must not undercount."""
    from horovod_tpu.runner.network import Wire

    wire = Wire(SECRET)
    assert isinstance(type(wire).tx_bytes, property)
    assert isinstance(type(wire).rx_bytes, property)
    frame = wire.frame(("payload", 123))
    global_before = global_registry().snapshot()[
        "horovod_wire_tx_bytes_total"]["samples"][0]["value"]
    sock = _NullSock()
    n, threads = 400, 8

    def writer() -> None:
        for _ in range(n):
            wire.write_frame(frame, sock)

    ts = [threading.Thread(target=writer) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert wire.tx_bytes == n * threads * len(frame)
    global_after = global_registry().snapshot()[
        "horovod_wire_tx_bytes_total"]["samples"][0]["value"]
    # >=: other live machinery in this process may also be framing
    assert global_after - global_before >= n * threads * len(frame)


def test_wire_rx_counter_counts_frames():
    from horovod_tpu.runner.network import Wire

    a, b = socket.socketpair()
    try:
        wire = Wire(SECRET)
        frame = wire.frame({"k": "v"})
        a.sendall(frame)
        assert wire.read(b) == {"k": "v"}
        assert wire.rx_bytes == len(frame)
    finally:
        a.close()
        b.close()


def test_controller_client_negotiation_properties():
    """negotiation_tx/rx_bytes live on as read-through properties (the
    back-compat satellite: controller_bench and the response-cache tests
    read them) while the canonical store is the registry."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import (
        DataType,
        Request,
        RequestList,
        RequestType,
    )

    assert isinstance(ControllerClient.negotiation_tx_bytes, property)
    assert isinstance(ControllerClient.negotiation_rx_bytes, property)
    cfg = Config.from_env()
    service = ControllerService(1, make_negotiator(1, cfg),
                                secret=SECRET, port=0)
    client = ControllerClient(("127.0.0.1", service.port), secret=SECRET)
    try:
        hist_before = global_registry().snapshot()[
            "horovod_negotiation_cycle_seconds"]["samples"][0]["count"]
        req = Request(request_rank=0, request_type=RequestType.ALLREDUCE,
                      tensor_name="obs.t", tensor_type=DataType.FLOAT32,
                      tensor_shape=(8,), root_rank=-1)
        client.cycle(0, RequestList(rank=0, requests=[req]))
        first_tx = client.negotiation_tx_bytes
        assert first_tx > 0
        assert first_tx == client.last_cycle_tx_bytes
        assert client.negotiation_rx_bytes == client.last_cycle_rx_bytes
        client.cycle(0, RequestList(rank=0, requests=[]))
        assert client.negotiation_tx_bytes > first_tx  # cumulative
        hist_after = global_registry().snapshot()[
            "horovod_negotiation_cycle_seconds"]["samples"][0]["count"]
        assert hist_after - hist_before >= 2  # latency histogram fed
    finally:
        client.close()
        service.shutdown()


def test_metrics_rpcs_refuse_foreign_world():
    """Co-located subset worlds share a controller port: a push or pull
    carrying a DIFFERENT world_id must be refused like "hello"/"watch" —
    storing it would merge another world's counters into this world's
    /metrics, and answering it would leak this world's store."""
    from horovod_tpu.ops.controller import (
        ControllerService,
        Negotiator,
        world_mismatch_error,
    )
    from horovod_tpu.runner.network import BasicClient, WireError

    svc = ControllerService(1, Negotiator(1, 1 << 26), secret=SECRET,
                            port=0, world_id="sub:0,1")
    client = BasicClient(("127.0.0.1", svc.port), secret=SECRET,
                         timeout_s=10.0, attempts=1)
    try:
        # matching (and legacy world-less) pushes land in the store
        assert client.request(("metrics", 0, {"f": 1}, "sub:0,1")) == ("ok",)
        assert client.request(("metrics", 1, {"f": 2})) == ("ok",)
        kind, store = client.request(("metrics_pull", "sub:0,1"))
        assert kind == "metrics" and set(store) == {0, 1}
        expected = world_mismatch_error("sub:0,1", "sub:9")
        with pytest.raises(WireError) as push_err:
            client.request(("metrics", 0, {"f": 3}, "sub:9"))
        assert expected in str(push_err.value)
        with pytest.raises(WireError) as pull_err:
            client.request(("metrics_pull", "sub:9"))
        assert expected in str(pull_err.value)
        assert svc.metrics_store()[0] == {"f": 1}  # foreign push not stored
    finally:
        client.close()
        svc.shutdown()


def test_histogram_reregistration_bounds_conflict():
    """The in-process twin of merge_snapshots' cross-rank bounds check:
    re-registering a histogram family with different buckets fails
    loudly instead of silently observing into the first caller's."""
    reg = Registry()
    h = reg.histogram("horovod_h_seconds", buckets=(0.01, 0.1))
    assert reg.histogram("horovod_h_seconds", buckets=(0.01, 0.1)) is h
    with pytest.raises(ValueError):
        reg.histogram("horovod_h_seconds", buckets=(0.5, 1.0))


# -- registry → timeline bridge -----------------------------------------------

def test_bridge_emits_deltas_and_skips_idle(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")  # python writer: the
    # test reads the file while close() semantics stay identical
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "bridge.json"
    tl = Timeline(str(path))
    reg = Registry()
    c = reg.counter("horovod_x_total")
    g = reg.gauge("horovod_g")
    h = reg.histogram("horovod_h_seconds", buckets=(0.1,))
    bridge = TimelineBridge(reg, tl)
    c.inc(5)
    g.set(2)
    h.observe(0.05)
    bridge.emit()
    bridge.emit()  # nothing changed: must add no records
    c.inc(1)
    bridge.emit()
    tl.close()
    records = [r for r in json.loads(path.read_text())
               if isinstance(r, dict) and r.get("ph") == "C"]
    by_name = {}
    for rec in records:
        by_name.setdefault(rec["name"], []).append(rec["args"])
    assert by_name["metrics/horovod_x_total"] == [
        {"value": 5}, {"value": 1}]  # deltas, idle emit skipped
    assert by_name["metrics/horovod_g"] == [{"value": 2}]  # absolute
    assert by_name["metrics/horovod_h_seconds"] == [{"count": 1}]


def test_bridge_noop_when_timeline_disabled():
    from horovod_tpu.utils.timeline import Timeline

    reg = Registry()
    reg.counter("horovod_x_total").inc()
    TimelineBridge(reg, Timeline("")).emit()  # must not raise


# -- 2-process acceptance -----------------------------------------------------

def _world_env(extra=None):
    env = {"HOROVOD_NATIVE_CONTROLLER": "0",  # the metrics-RPC wire
           "HOROVOD_CYCLE_TIME": "2",
           "HOROVOD_PLATFORM": "cpu"}
    env.update(extra or {})
    return env


def _run_world(fn, args, np_, extra_env):
    """runner.run with env pins applied around the call (runner exports
    the parent env to every worker)."""
    from horovod_tpu.runner import run

    saved = {k: os.environ.get(k) for k in extra_env}
    os.environ.update(extra_env)
    try:
        return run(fn, args=args, np=np_, timeout_s=180.0,
                   start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _obs_world_fn(steps, port):
    """Cache-steady workload; rank 0 scrapes its own exposition server
    once every rank's publisher has pushed. The pre-shutdown barrier
    keeps the world (and its publishers) alive through the scrape."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import json as _json
    import time as _time
    import urllib.request as _url

    import numpy as _np

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    for _ in range(steps):
        out = hvd.allreduce(_np.full((32,), float(rank + 1), _np.float32),
                            average=False, name="obs.steady")
        _np.testing.assert_array_equal(
            _np.asarray(out), float(sum(range(1, size + 1))))
    doc = None
    if rank == 0:
        deadline = _time.monotonic() + 15.0
        while _time.monotonic() < deadline:
            if len(hvd.metrics_snapshot(world=True)["ranks"]) >= size:
                break
            _time.sleep(0.2)
        prom = _url.urlopen(f"http://127.0.0.1:{port}/metrics",
                            timeout=10).read().decode()
        doc = _json.loads(_url.urlopen(
            f"http://127.0.0.1:{port}/metrics.json",
            timeout=10).read().decode())
        doc["_prom"] = prom
    hvd.allreduce(_np.zeros((1,), _np.float32), name="obs.done")
    hvd.shutdown()
    return doc


def test_mp_world_aggregation_and_prometheus():
    """The acceptance criterion: a 2-process run serves /metrics with
    world-aggregated histograms whose bucket sums equal the per-rank
    sums, during an all-hit cache steady state, without perturbing the
    negotiation cycle (the workload asserts its own results)."""
    port = _free_port()
    results = _run_world(
        _obs_world_fn, (6, port), 2,
        _world_env({"HOROVOD_METRICS_PORT": str(port),
                    "HOROVOD_METRICS_INTERVAL_S": "0.2"}))
    doc = [r for r in results if r is not None][0]
    types = parse_prometheus(doc["_prom"])
    for family in ("horovod_negotiation_cycle_seconds",
                   "horovod_cache_hit_cycles_total",
                   "horovod_wire_tx_bytes_total"):
        assert family in types, sorted(types)
    assert len(doc["ranks"]) == 2, sorted(doc["ranks"])
    world_h = doc["world"]["horovod_negotiation_cycle_seconds"][
        "samples"][0]
    rank_hs = [r["horovod_negotiation_cycle_seconds"]["samples"][0]
               for r in doc["ranks"].values()]
    assert world_h["buckets"] == [
        sum(col) for col in zip(*[h["buckets"] for h in rank_hs])]
    assert world_h["count"] == sum(h["count"] for h in rank_hs) > 0
    # the steady state reached the bypass and the metrics plane saw it
    hits = doc["world"]["horovod_cache_hit_cycles_total"][
        "samples"][0]["value"]
    assert hits > 0, doc["world"]["horovod_cache_hit_cycles_total"]


def _obs_exactness_fn(steps, metrics_on):
    """Fixed workload whose per-rank result digest must be bit-identical
    with the observability plane on or off; with it on, rank 1 also
    pulls a world snapshot mid-run (over a transient connection) and the
    run rides a chaos-injected reconnect."""
    import hashlib as _hashlib

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    digest = _hashlib.sha256()
    for step in range(steps):
        out = hvd.allreduce(
            _np.full((64,), float(rank + 1) * (step + 1), _np.float32),
            average=False, name="obs.exact")
        digest.update(_np.asarray(out).tobytes())
        if metrics_on and rank == 1 and step == steps // 2:
            world = hvd.metrics_snapshot(world=True)  # mid-run pull
            assert "world" in world and world["ranks"], world
    hvd.allreduce(_np.zeros((1,), _np.float32), name="obs.exact.done")
    hvd.shutdown()
    return digest.hexdigest()


def test_mp_bit_exact_with_metrics_and_chaos_vs_off():
    """The acceptance criterion: snapshot pulls during a chaos-injected
    reconnect succeed, and the training result is bit-exact with metrics
    on vs off (the plane observes, never participates)."""
    port = _free_port()
    on = _run_world(
        _obs_exactness_fn, (8, True), 2,
        _world_env({"HOROVOD_METRICS_PORT": str(port),
                    "HOROVOD_METRICS_INTERVAL_S": "0.2",
                    "HOROVOD_CHAOS": "drop@rank1:msg5"}))
    off = _run_world(_obs_exactness_fn, (8, False), 2, _world_env())
    assert len(set(on)) == 1  # identical on every rank
    assert set(on) == set(off), (on, off)  # bit-exact, metrics on vs off


def _final_flush_fn():
    """Publisher interval far longer than the whole job: the ONLY way a
    rank's snapshot can reach the coordinator's store is the final flush
    at engine teardown (the eager-dialed connection outlives the
    negotiated shutdown's listener close). No pre-shutdown barrier — the
    exact shutdown-ordering fragility PR 5's dryrun documented."""
    import time as _time

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank = hvd.rank()
    for _ in range(3):
        hvd.allreduce(_np.ones((8,), _np.float32), name="obs.flush")
    engine = get_engine()  # keep a handle past shutdown's global clear
    hvd.shutdown()
    if rank != 0:
        return []
    service = engine._service
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        store = service.metrics_store()
        if len(store) >= 2:
            break
        _time.sleep(0.05)
    # every stored snapshot is a FINAL one: it carries the rank's full
    # cycle count, not an empty pre-first-interval registry
    return sorted(
        (r, s["horovod_negotiation_cycles_total"]["samples"][0]["value"])
        for r, s in service.metrics_store().items())


def test_publisher_final_flush_beats_shutdown_ordering():
    """The final partial interval must not be silently lost: with a 60 s
    interval the store can only be populated by the teardown flush, from
    BOTH ranks, each with its complete final counters."""
    entries = [r for r in _run_world(
        _final_flush_fn, (), 2,
        _world_env({"HOROVOD_METRICS_INTERVAL_S": "60"})) if r][0]
    assert [r for r, _ in entries] == [0, 1], entries
    for _rank, cycles in entries:
        assert cycles > 0, entries


# -- elastic interplay (wall-clock heavy: slow tier) --------------------------

@pytest.mark.slow
def test_metrics_survive_elastic_restart():
    """A relaunched world's registry starts fresh (new processes) with
    the epoch gauge bumped — the metrics plane keeps working across the
    detect→abort→relaunch→restore path."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _elastic_metrics_fn, args=(), np=2, min_np=2, max_restarts=2,
        backoff_s=0.1, timeout_s=120.0, start_timeout_s=120.0,
        heartbeat_interval_s=0.5, heartbeat_miss_limit=6,
        env_extra=_world_env())
    for snap in results:
        assert snap["epoch"] == 1
        assert snap["cycles"] > 0


def _elastic_metrics_fn():
    import os as _os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch

    hvd.init()
    if world_epoch() == 0 and hvd.rank() == 1:
        _os._exit(11)  # first attempt dies; relaunch must re-meter
    for _ in range(3):
        hvd.allreduce(_np.ones((8,), _np.float32), name="obs.el")
    local = hvd.metrics_snapshot()
    hvd.shutdown()
    return {
        "epoch": local["horovod_elastic_world_epoch"][
            "samples"][0]["value"],
        "cycles": local["horovod_negotiation_cycles_total"][
            "samples"][0]["value"],
    }
