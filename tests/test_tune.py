"""Closed-loop tuning plane + straggler mitigation tests (docs/autotune.md).

Unit coverage of the pure-Python policy (baseline → retune cadence, knob
bounds, pinning, the best-known-config revert guard, the deterministic
regress@N fault hook, the JSONL decision sink), the two-gated sliding-
window straggler detector, the Autotuner facade (policy backend without
the native core; the CSV header-once-per-file fix), live
ControllerService coverage of decision application (extended knobs
piggybacked on the cycle wire; fusion/codec retunes bumping the
response-cache generation warm — mirrors the PR-3 interplay test), the
elastic driver's advisory RPC, and — under ``slow`` — the 2-proc
certification dryruns (multi-retune + eviction soaks).

Named test_tune.py so it sorts after the 870 s tier-1 truncation point
(ROADMAP operational note), like test_metrics/test_tracing before it.
"""

import json
import time

import pytest

from horovod_tpu.core.config import Config
from horovod_tpu.ops.autotuner import Autotuner
from horovod_tpu.ops.controller import (
    ControllerClient,
    ControllerService,
    Negotiator,
)
from horovod_tpu.ops.messages import (
    CacheHitAck,
    CacheRequest,
    DataType,
    Request,
    RequestList,
    RequestType,
    ResponseList,
    ResponseType,
)
from horovod_tpu.ops.response_cache import bits_of
from horovod_tpu.tune import (
    Decision,
    Knob,
    StragglerDetector,
    TuningPolicy,
    default_knobs,
    parse_fault,
)

pytestmark = pytest.mark.tune

SECRET = b"s" * 32


def _knobs(**pins):
    return [
        Knob("fusion_threshold_bytes", (1 << 20, 1 << 21, 1 << 22), 1,
             pinned=pins.get("fusion", False)),
        Knob("cycle_time_ms", (1.0, 2.5, 5.0), 1,
             pinned=pins.get("cycle", False)),
    ]


def _drive(policy, score, cycles):
    """Feed ``cycles`` constant-score observations; collect decisions."""
    out = []
    for _ in range(cycles):
        d = policy.observe(score * 1e3, 1e3)  # bytes/us == score
        if d is not None:
            out.append(d)
    return out


# -- policy: cadence, bounds, pins, revert guard ------------------------------

def test_policy_baseline_then_first_retune_cadence():
    policy = TuningPolicy(_knobs(), window=3, cooldown=2)
    decisions = []
    for i in range(5):
        d = policy.observe(1e6, 1e3)
        decisions.append(d)
        # baseline window is 3 scored cycles; nothing may move before it
        if i < 2:
            assert d is None, (i, d)
    assert decisions[2] is not None and decisions[2].action == "retune"
    # the 2-cycle cooldown after the move discards the next samples
    assert decisions[3] is None and decisions[4] is None


def test_policy_bounds_respected_under_greedy_improvement():
    policy = TuningPolicy(_knobs(), window=1, cooldown=0)
    score = 1.0
    for _ in range(200):
        policy.observe(score * 1e3, 1e3)
        score *= 1.05  # every window improves: pure greed
    for knob in _knobs():
        value = policy.value(knob.name)
        assert min(knob.values) <= value <= max(knob.values), (
            knob.name, value)


def test_policy_pinned_knobs_never_move():
    policy = TuningPolicy(_knobs(cycle=True), window=1, cooldown=0)
    score = 1.0
    for i in range(100):
        policy.observe(score * 1e3, 1e3)
        score *= 1.02 if i % 3 else 0.5  # improvements AND regressions
    assert policy.value("cycle_time_ms") == 2.5  # the pinned start value


def test_revert_guard_restores_best_within_one_window():
    policy = TuningPolicy(_knobs(), window=1, cooldown=0, tolerance=0.05)
    baseline_config = policy.config()
    # the baseline window immediately proposes the first move
    moves = _drive(policy, 10.0, 1)
    assert [d.action for d in moves] == ["retune"]
    assert policy.config() != baseline_config
    # the move's measured window regresses hard: the VERY NEXT decision
    # must be the rollback to the best-known (baseline) config
    reverts = _drive(policy, 2.0, 1)
    assert [d.action for d in reverts] == ["revert"]
    assert reverts[0].config == baseline_config
    assert policy.config() == baseline_config
    assert policy.reverts == 1


def test_flat_landscape_converges_to_idle_not_pingpong():
    """Review regression: a knob whose effect stays inside the tolerance
    band must not oscillate forever — every fusion ping was a REAL change
    bumping the cache generation. Strict acceptance discards flat moves
    and the re-explore backoff decays the churn toward idle."""
    policy = TuningPolicy(_knobs(), window=1, cooldown=0)
    per_window = []
    for _ in range(120):  # perfectly flat scores
        per_window.append(policy.observe(5e3, 1e3))
    # every flat retune is immediately discarded back to baseline —
    # no kept flat moves, no guard reverts, no config drift
    assert policy.config() == {k.name: k.current for k in _knobs()}
    assert policy.reverts == 0
    assert policy.retunes == policy.discards > 0
    # and the churn DECAYS (doubling re-explore backoff) instead of
    # repeating at a fixed cadence: the last third must be mostly idle
    early = sum(1 for d in per_window[:40] if d is not None)
    late = sum(1 for d in per_window[-40:] if d is not None)
    assert late < early / 2, (early, late)


def test_best_score_reanchors_under_online_drift():
    """When the BEST-KNOWN config itself scores lower (workload change,
    no move to blame), the guard must re-anchor instead of judging every
    future move against a stale, unreachable score."""
    policy = TuningPolicy(_knobs(), window=1, cooldown=0)
    _drive(policy, 10.0, 1)   # baseline 10 + first move
    _drive(policy, 2.0, 1)    # the move regressed: revert to baseline
    assert policy.reverts == 1
    _drive(policy, 3.0, 1)    # baseline itself now scores 3: re-anchor
    assert policy.best["score_bytes_per_us"] == 3.0
    _drive(policy, 3.0, 1)    # the next move is judged against 3, not 10
    assert policy.reverts == 1


def test_improvement_adopts_new_best_config():
    policy = TuningPolicy(_knobs(), window=1, cooldown=0)
    moves = _drive(policy, 1.0, 1)  # baseline + first proposed move
    assert moves and moves[0].action == "retune"
    _drive(policy, 5.0, 1)          # the move improved: new best adopted
    assert policy.best["config"][moves[0].knob] == moves[0].value
    assert policy.best["score_bytes_per_us"] == 5.0


def test_forced_regression_exactly_one_revert():
    policy = TuningPolicy(_knobs(), window=1, cooldown=0,
                          fault="regress@2")
    # real scores are IGNORED under the fault (synthetic plateau), so a
    # deliberately noisy stream must not add extra reverts
    import random

    rng = random.Random(7)
    for _ in range(100):
        policy.observe(rng.uniform(0.1, 20.0) * 1e3, 1e3)
    assert policy.reverts == 1
    assert policy.retunes >= 2


def test_fault_spec_typo_fails_loudly():
    with pytest.raises(ValueError, match="HOROVOD_AUTOTUNE_FAULT"):
        parse_fault("regress@soon")
    with pytest.raises(ValueError, match="HOROVOD_AUTOTUNE_FAULT"):
        TuningPolicy(_knobs(), fault="regresss@2")
    assert parse_fault("") is None
    assert parse_fault("regress@3") == ("regress", 3)


def test_decision_sink_receives_jsonable_records():
    records = []
    policy = TuningPolicy(_knobs(), window=1, cooldown=0,
                          decision_sink=records.append)
    _drive(policy, 1.0, 3)
    assert records[0]["action"] == "init"
    assert any(r["action"] == "retune" for r in records)
    for record in records:
        json.dumps(record)  # the JSONL log contract
        assert "config" in record


def test_default_knobs_gating_and_pins():
    cfg = Config(cache_capacity=1024, metrics_port=9100)
    names = {k.name for k in default_knobs(cfg, extended=True)}
    assert names == {"fusion_threshold_bytes", "cycle_time_ms",
                     "cache_capacity", "metrics_interval_s", "codec",
                     "fusion_subbuffers"}
    # classic pair only without the extended (Python-controller) wire
    names = {k.name for k in default_knobs(cfg, extended=False)}
    assert names == {"fusion_threshold_bytes", "cycle_time_ms"}
    # codec is PINNED without the explicit opt-in allowlist...
    by_name = {k.name: k for k in default_knobs(cfg, extended=True)}
    assert by_name["codec"].pinned
    # ...and unpinned (ladder = none + allowlist) with it
    cfg2 = Config(cache_capacity=1024, autotune_codecs=("int8", "fp8"))
    by_name = {k.name: k for k in default_knobs(cfg2, extended=True)}
    assert not by_name["codec"].pinned
    assert by_name["codec"].values == ("none", "int8", "fp8")
    # explicit env values pin their knobs; capacity 0 drops the knob
    cfg3 = Config(cache_capacity=0, fusion_threshold_explicit=True,
                  cycle_time_explicit=True,
                  fusion_subbuffers_explicit=True)
    knobs = default_knobs(cfg3, extended=True)
    assert {k.name for k in knobs} == {"fusion_threshold_bytes",
                                       "cycle_time_ms", "codec",
                                       "fusion_subbuffers"}
    assert all(k.pinned for k in knobs)
    # the ladder always starts AT the live value
    cfg4 = Config(cycle_time_ms=3.3)
    by_name = {k.name: k for k in default_knobs(cfg4)}
    assert by_name["cycle_time_ms"].current == 3.3
    # a codec allowlist typo must fail loudly, not silently pin the knob
    with pytest.raises(ValueError, match="HOROVOD_AUTOTUNE_CODECS"):
        default_knobs(Config(autotune_codecs=("in8",)), extended=True)


# -- straggler detector: two gates, persistence, rate limit -------------------

def _detector(**kw):
    kw.setdefault("mode", "advisory")
    kw.setdefault("window_s", 30.0)
    kw.setdefault("min_cycles", 10)
    return StragglerDetector(4, **kw)


def test_detector_needs_min_cycles(monkeypatch):
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=10)
    for _ in range(9):
        assert det.observe_cycle(1, 0.050) is None
    assert det.observe_cycle(1, 0.050) is not None  # the 10th fires


def test_detector_spread_floor_gates_verdict(monkeypatch):
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=5, min_spread_s=0.005)
    # one rank owns 100% of the blame, but spreads are scheduler jitter
    for _ in range(50):
        assert det.observe_cycle(2, 0.0001) is None


def test_detector_blame_seconds_beat_counts(monkeypatch):
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=5)
    verdicts = []
    for i in range(30):
        # rank 1 is late by microseconds on MOST cycles; rank 3 by 50 ms
        # on a third of them — the seconds, not the counts, must decide
        if i % 3:
            v = det.observe_cycle(1, 0.000030)
        else:
            v = det.observe_cycle(3, 0.050)
        if v:
            verdicts.append(v)
    assert verdicts and all(v["rank"] == 3 for v in verdicts)


def test_detector_one_advisory_per_window(monkeypatch):
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=5, window_s=30.0)
    fired = [det.observe_cycle(1, 0.050) for _ in range(100)]
    assert len([f for f in fired if f]) == 1  # rate-limited per window


def test_detector_refire_carries_a_new_seq(monkeypatch):
    """A persistent straggler re-advises once per window, and each refire
    carries a higher seq — the driver's per-rank store overwrites, so seq
    is what keeps its eviction counter counting (review finding)."""
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=5, window_s=0.2)
    fired = []
    deadline = time.monotonic() + 1.0
    while time.monotonic() < deadline:
        v = det.observe_cycle(1, 0.050)
        if v:
            fired.append(v)
        time.sleep(0.01)
    assert len(fired) >= 2, fired  # still a straggler → re-advised
    assert [f["seq"] for f in fired] == list(range(1, len(fired) + 1))


def test_detector_window_prunes_old_blame(monkeypatch):
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    det = _detector(min_cycles=5, window_s=0.2)
    for _ in range(20):
        det.observe_cycle(1, 0.050)
    time.sleep(0.3)  # the whole window ages out
    assert len(det._events) == 20  # pruned lazily on the next feed
    assert det.observe_cycle(2, 0.000001) is None
    assert len(det._events) == 1


def test_detector_bad_mode_fails_loudly():
    with pytest.raises(ValueError, match="HOROVOD_STRAGGLER_EVICT"):
        StragglerDetector(2, mode="advsory")  # the typo must not be "off"


# -- Autotuner facade: backends + CSV header fix ------------------------------

def test_policy_backend_needs_no_native_core(monkeypatch):
    from horovod_tpu import cc

    monkeypatch.setattr(cc, "available", lambda: False)
    tuner = Autotuner(Config(autotune=True, autotune_window=1,
                             autotune_cooldown=0))
    try:
        decisions = [tuner.observe(1e6, 1e3) for _ in range(5)]
        assert any(d is not None for d in decisions)
    finally:
        tuner.close()
    with pytest.raises(RuntimeError, match="native core"):
        Autotuner(Config(autotune=True, autotune_backend="native"))
    with pytest.raises(ValueError, match="HOROVOD_AUTOTUNE_BACKEND"):
        Autotuner(Config(autotune=True, autotune_backend="bayes"))


def test_csv_header_written_once_across_restarts(tmp_path):
    """Satellite regression: the sample log opens in append mode, and a
    restarted run used to write a SECOND header row mid-file."""
    log = str(tmp_path / "autotune.csv")
    for _ in range(3):  # three "runs" appending to one file
        tuner = Autotuner(Config(autotune=True, autotune_log=log,
                                 autotune_window=1, autotune_cooldown=0))
        tuner.observe(1e6, 1e3)
        tuner.close()
    lines = open(log, encoding="utf-8").read().strip().splitlines()
    headers = [l for l in lines if l.startswith("timestamp,")]
    assert len(headers) == 1, lines
    assert lines[0] == headers[0]
    assert len(lines) == 4  # header + one sample per run


@pytest.mark.skipif(
    not __import__("horovod_tpu.cc", fromlist=["cc"]).available(),
    reason="the native GP backend needs the native core")
def test_native_backend_decisions_reach_the_jsonl_log(tmp_path):
    """The policy sinks its own decisions; the facade must keep the JSONL
    audit complete for the native GP too (review finding)."""
    path = str(tmp_path / "native.jsonl")
    tuner = Autotuner(Config(autotune=True, autotune_backend="native",
                             autotune_decisions=path))
    try:
        # the GP needs varied samples before it moves; drive until it does
        for i in range(2000):
            if tuner.observe(1e6 * (1 + (i % 7)), 1e3 * (1 + (i % 3))):
                break
    finally:
        tuner.close()
    records = [json.loads(l) for l in open(path, encoding="utf-8")]
    assert records[0]["action"] == "init"
    assert records[0]["backend"] == "native"
    assert any(r["action"] == "retune" for r in records), records


def test_decision_log_appends_across_restarts(tmp_path):
    path = str(tmp_path / "decisions.jsonl")
    for _ in range(2):
        tuner = Autotuner(Config(autotune=True, autotune_decisions=path,
                                 autotune_window=1, autotune_cooldown=0))
        for _ in range(3):
            tuner.observe(1e6, 1e3)
        tuner.close()
    records = [json.loads(l) for l in open(path, encoding="utf-8")]
    assert sum(1 for r in records if r["action"] == "init") == 2
    assert all("t" in r for r in records)


# -- live service: decision application + cache interplay (the PR-3 mirror) ---

class _ScriptedTuner:
    """Stands in for the Autotuner: returns the scripted Decision on the
    Nth scored cycle, None elsewhere."""

    def __init__(self, script):  # {cycle_no: Decision}
        self._script = dict(script)
        self._cycle = 0

    def observe_cycle(self, response_list, active_us=None):
        decision = self._script.pop(self._cycle, None)
        self._cycle += 1
        return decision

    def close(self):
        pass


def _decision(**config):
    base = {"fusion_threshold_bytes": 1 << 26, "cycle_time_ms": 3.0}
    base.update(config)
    return Decision(action="retune", knob=next(iter(config), "none"),
                    value=None, score=1.0, best_score=1.0, config=base)


def _req(name, shape=(8,), rank=0):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=tuple(shape), root_rank=-1)


def _drive_cycles(service, plans):
    """Single-rank world: run one cycle per plan (list of Requests or
    'hit' for a full-cache bitvector), returning the raw replies."""
    client = ControllerClient(("127.0.0.1", service.port), secret=SECRET,
                              rank=0)
    out = []
    try:
        for plan in plans:
            if plan == "hit":
                cache = service._cache
                positions = sorted(cache._entries)
                reply = client.cycle(0, CacheRequest(
                    rank=0, bits=bits_of(positions, cache.capacity),
                    generation=cache.generation))
            else:
                reply = client.cycle(0, RequestList(rank=0, requests=plan))
            out.append(reply)
    finally:
        client.close()
    return out


def test_extended_decision_piggybacks_and_resizes_cache_warm():
    """A cache-capacity retune must ride the cycle wire (tuned_knobs),
    bump the generation (both mirrors clear), resize at the deferred
    bookkeeping point, and leave the world warm-cacheable again."""
    service = ControllerService(1, Negotiator(1, 1 << 26), secret=SECRET,
                                port=0, cache_capacity=16,
                                fusion_threshold_bytes=1 << 26,
                                autotuner=_ScriptedTuner({
                                    2: _decision(cache_capacity=8,
                                                 metrics_interval_s=7.0)}))
    try:
        replies = _drive_cycles(service, [
            [_req("g0")], "hit", [_req("g1")], [_req("g2")], "hit"])
    finally:
        service.shutdown()
    gen0 = replies[0].cache_generation
    assert isinstance(replies[1], CacheHitAck)
    # cycle 2 carried the decision: new generation + the knob map
    assert replies[2].cache_generation == gen0 + 1
    assert replies[2].tuned_knobs == {"cache_capacity": 8,
                                      "metrics_interval_s": 7.0}
    assert replies[2].tuned_cycle_ms == 3.0
    assert service._cache.capacity == 8
    # the map keeps riding every later response (late joiner semantics)
    assert replies[3].tuned_knobs == replies[2].tuned_knobs
    # and the resized cache serves acks again (warm after one miss)
    assert isinstance(replies[4], CacheHitAck)
    assert replies[4].tuned_knobs == replies[2].tuned_knobs


def test_fusion_retune_bumps_generation_warm():
    """The PR-3 interplay contract through the DECISION path: a tuned
    fusion threshold must invalidate cached fused layouts."""
    service = ControllerService(1, Negotiator(1, 1 << 26), secret=SECRET,
                                port=0, cache_capacity=16,
                                fusion_threshold_bytes=1 << 26,
                                autotuner=_ScriptedTuner({
                                    2: _decision(
                                        fusion_threshold_bytes=1)}))
    try:
        replies = _drive_cycles(service, [
            [_req("a"), _req("b")], "hit", [_req("c")],
            [_req("a"), _req("b")]])
    finally:
        service.shutdown()
    gen0 = replies[0].cache_generation
    assert replies[2].cache_generation == gen0 + 1  # repack → bump
    # renegotiated under the 64-byte threshold: the pair no longer fuses
    assert len(replies[3].responses) == 2, replies[3]


def test_codec_retune_rewrites_responses_and_bumps_generation():
    """Codec application is a coordinator-side RESPONSE rewrite (requests
    stay uniform — no mid-flight negotiation divergence) restricted to
    the large tensor class, and a codec flip invalidates the warm cache
    exactly like a fusion repack."""
    # fusion threshold 1: responses never fuse, so the big/small tensor
    # classes stay separate batches the rewrite floor can discriminate
    service = ControllerService(1, Negotiator(1, 1), secret=SECRET,
                                port=0, cache_capacity=16,
                                fusion_threshold_bytes=1,
                                codec_min_bytes=1024,
                                autotuner=_ScriptedTuner({
                                    1: _decision(codec="none",
                                                 fusion_threshold_bytes=1),
                                    3: _decision(codec="int8",
                                                 fusion_threshold_bytes=1)}))
    try:
        replies = _drive_cycles(service, [
            [_req("big", shape=(1024,))], "hit", "hit",
            [_req("small")],
            [_req("big", shape=(1024,)), _req("small")]])
    finally:
        service.shutdown()
    gen0 = replies[0].cache_generation
    # decision 1 set codec="none" (the baseline): NO bump, still warm
    assert isinstance(replies[2], CacheHitAck)
    assert replies[2].generation == gen0
    # decision 3 flipped to int8: generation bump on the next response
    assert replies[3].cache_generation == gen0 + 1
    by_name = {tuple(r.tensor_names): r for r in replies[4].responses}
    assert by_name[("big",)].tensor_codec == "int8"   # large class
    assert by_name[("small",)].tensor_codec == "none"  # below the floor
    assert replies[4].tuned_knobs["codec"] == "int8"


def test_first_decision_codec_flip_still_bumps():
    """Review regression: when codec is the only unpinned knob, the FIRST
    decision can already carry the flip — never-applied must read as the
    'none' baseline, or warm cached layouts keep replaying the
    full-precision wire forever."""
    service = ControllerService(1, Negotiator(1, 1), secret=SECRET,
                                port=0, cache_capacity=16,
                                fusion_threshold_bytes=1,
                                codec_min_bytes=1024,
                                autotuner=_ScriptedTuner({
                                    1: _decision(codec="int8",
                                                 fusion_threshold_bytes=1)}))
    try:
        replies = _drive_cycles(service, [
            [_req("big", shape=(1024,))], "hit",
            [_req("big", shape=(1024,))]])
    finally:
        service.shutdown()
    gen0 = replies[0].cache_generation
    # the flip landed on the ack cycle: its generation is already bumped,
    # so the warm layout cannot replay under the stale codec
    assert replies[1].generation == gen0 + 1, replies[1]
    assert replies[2].responses[0].tensor_codec == "int8"


# -- elastic: the advisory RPC + driver mode validation -----------------------

def test_advise_evict_rpc_epoch_fenced():
    from horovod_tpu.elastic.health import ElasticService
    from horovod_tpu.runner.network import BasicClient

    service = ElasticService(SECRET, heartbeat_interval_s=0.2)
    try:
        service.begin_epoch(1)
        client = BasicClient(("127.0.0.1", service.port), secret=SECRET)
        try:
            client.request(("advise_evict", 0, 2, {"blame_share": 0.9}))
            assert service.evict_advisories() == {}  # stale epoch fenced
            client.request(("advise_evict", 1, 2, {"blame_share": 0.9}))
            advisories = service.evict_advisories()
            assert advisories[2]["blame_share"] == 0.9
            service.begin_epoch(2)  # relaunch resets the table
            assert service.evict_advisories() == {}
        finally:
            client.close()
    finally:
        service.shutdown()


def test_detector_pushes_advisory_to_elastic_service(monkeypatch):
    from horovod_tpu.elastic.health import ElasticService

    service = ElasticService(SECRET, heartbeat_interval_s=0.2)
    try:
        monkeypatch.setenv("HOROVOD_ELASTIC_ADDR", "127.0.0.1")
        monkeypatch.setenv("HOROVOD_ELASTIC_PORT", str(service.port))
        monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "0")
        monkeypatch.setenv("HOROVOD_SECRET_KEY", SECRET.hex())
        det = StragglerDetector(2, mode="advisory", window_s=30.0,
                                min_cycles=5)
        for _ in range(5):
            det.observe_cycle(1, 0.050)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if service.evict_advisories():
                break
            time.sleep(0.05)
        advisories = service.evict_advisories()
        assert advisories and advisories[1]["rank"] == 1, advisories
        assert advisories[1]["blame_share"] == 1.0
    finally:
        service.shutdown()


def test_run_elastic_rejects_bad_mode():
    from horovod_tpu.runner import run_elastic

    with pytest.raises(ValueError, match="straggler_evict"):
        run_elastic(lambda: None, np=1, straggler_evict="evict-hard")


# -- certification soaks (the driver's acceptance runs) -----------------------

@pytest.mark.slow
def test_dryrun_autotune():
    """Acceptance: 2-proc no-native-core world makes >= 2 retunes
    bit-exact vs tuning off; regress@2 produces exactly one revert."""
    import __graft_entry__ as g

    g.dryrun_autotune()


@pytest.mark.slow
def test_dryrun_straggler_evict():
    """Acceptance: chaos delay@rank1 world names rank 1 (advisory
    received / enforce acted on); clean world raises zero advisories."""
    import __graft_entry__ as g

    g.dryrun_straggler_evict()
