"""TensorFlow front-end (reference: ``test/test_tensorflow.py`` op tests +
``test/test_tensorflow_keras.py`` end-to-end fit, run against the
TPU-native engine)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402
import horovod_tpu.tensorflow.keras as hvd_keras  # noqa: E402


def test_tf_allreduce_roundtrip(hvd):
    t = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    out = hvd_tf.allreduce(t, average=False, name="tf.ar")
    assert isinstance(out, tf.Tensor)
    np.testing.assert_array_equal(out.numpy(), t.numpy())


def test_tf_bf16_roundtrip(hvd):
    t = tf.cast(tf.ones((4,)), tf.bfloat16)
    out = hvd_tf.allreduce(t, average=True, name="tf.bf16")
    assert out.dtype == tf.bfloat16
    np.testing.assert_array_equal(tf.cast(out, tf.float32).numpy(), 1.0)


def test_tf_fp16_compression(hvd):
    t = tf.constant([1.0, 2.0, 3.0])
    out = hvd_tf.allreduce(t, name="tf.fp16",
                           compression=hvd_tf.Compression.fp16)
    assert out.dtype == tf.float32  # decompressed back
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-3)


def test_tf_broadcast_and_allgather(hvd):
    t = tf.fill((3,), 5.0)
    np.testing.assert_array_equal(
        hvd_tf.broadcast(t, 0, name="tf.b").numpy(), 5.0)
    np.testing.assert_array_equal(
        hvd_tf.allgather(t, name="tf.g").numpy(), t.numpy())


def test_tf_indexed_slices_allreduce(hvd):
    s = tf.IndexedSlices(values=tf.ones((2, 3)),
                         indices=tf.constant([0, 2]),
                         dense_shape=tf.constant([4, 3]))
    out = hvd_tf.allreduce(s, average=True, name="tf.sparse")
    assert isinstance(out, tf.IndexedSlices)
    np.testing.assert_array_equal(out.values.numpy(), 1.0)
    np.testing.assert_array_equal(out.indices.numpy(), [0, 2])


def test_tf_function_graph_mode(hvd):
    @tf.function
    def step(x):
        return hvd_tf.allreduce(x, average=False, name="tf.graph.ar")

    t = tf.constant([1.0, 2.0])
    np.testing.assert_array_equal(step(t).numpy(), t.numpy())


def test_jit_compile_boundary_is_fenced(hvd):
    """The graph path cannot compile under jit_compile=True (EagerPyFunc
    has no XLA kernel; undetectable at trace time). The fence is the op
    name: XLA's error must quote the self-explanatory node name so the
    user lands on the remedy (docs/parity.md 'TF compile boundary')."""

    @tf.function(jit_compile=True)
    def step(x):
        return hvd_tf.allreduce(x, average=False, name="tf.jit.ar")

    with pytest.raises(tf.errors.InvalidArgumentError) as exc_info:
        step(tf.constant([1.0, 2.0]))
    assert "not_XLA_compilable" in str(exc_info.value)
    assert "JAX_frontend" in str(exc_info.value)


def test_distributed_gradient_tape(hvd):
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(v * v)
    tape = hvd_tf.DistributedGradientTape(tape)
    grads = tape.gradient(loss, [v])
    np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0])


def test_tf_collectives_differentiable(hvd):
    """The collectives carry gradients (reference ``mpi_ops.py:94-183``
    registrations): at size 1 each op is identity, so the tape gradient of
    sum(op(x) * w) is w."""
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd_tf

    x = tf.Variable([1.0, 2.0, 3.0])
    w = tf.constant([2.0, 3.0, 4.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.allreduce(x, average=False,
                                              name="g.ar") * w)
    np.testing.assert_array_equal(tape.gradient(loss, x).numpy(), w.numpy())

    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.allgather(x, name="g.gather"))
    np.testing.assert_array_equal(tape.gradient(loss, x).numpy(),
                                  np.ones(3, np.float32))

    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd_tf.broadcast(x, root_rank=0,
                                              name="g.bcast") * 2.0)
    np.testing.assert_array_equal(tape.gradient(loss, x).numpy(),
                                  np.full(3, 2.0, np.float32))


def test_broadcast_variables(hvd):
    var = tf.Variable([5.0, 6.0])
    hvd_tf.broadcast_variables([var], root_rank=0)
    np.testing.assert_array_equal(var.numpy(), [5.0, 6.0])


def test_broadcast_global_variables_rejects_eager(hvd):
    with pytest.raises(RuntimeError, match="eager"):
        hvd_tf.broadcast_global_variables(0)


def test_keras_distributed_optimizer_fit(hvd):
    np.random.seed(0)
    keras.utils.set_random_seed(0)
    X = np.random.randn(64, 4).astype(np.float32)
    Y = (X @ np.array([1.0, -2.0, 3.0, 0.5], np.float32) + 1.0)[:, None]
    model = keras.Sequential([keras.layers.Dense(8, activation="relu"),
                              keras.layers.Dense(1)])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.05, momentum=0.9))
    model.compile(optimizer=opt, loss="mse")
    hist = model.fit(X, Y, batch_size=16, epochs=3, verbose=0,
                     callbacks=[
                         hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                         hvd_keras.callbacks.MetricAverageCallback()])
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_keras_lr_warmup_logs_lr(hvd):
    np.random.seed(0)
    X = np.random.randn(32, 2).astype(np.float32)
    Y = X.sum(axis=1, keepdims=True)
    model = keras.Sequential([keras.layers.Dense(1)])
    model.compile(optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1)), loss="mse")
    hist = model.fit(
        X, Y, batch_size=16, epochs=2, verbose=0,
        callbacks=[hvd_keras.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=2)])
    assert "lr" in hist.history
    # size-1 world: warmup multiplier is identically 1 -> lr unchanged
    np.testing.assert_allclose(hist.history["lr"], 0.1, rtol=1e-6)


def test_keras_save_load_roundtrip(hvd, tmp_path):
    np.random.seed(0)
    X = np.random.randn(32, 4).astype(np.float32)
    Y = X.sum(axis=1, keepdims=True)
    model = keras.Sequential([keras.layers.Dense(1)])
    model.compile(optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.05, momentum=0.9)), loss="mse")
    model.fit(X, Y, batch_size=16, epochs=1, verbose=0)
    path = str(tmp_path / "model.keras")
    model.save(path)

    loaded = hvd_keras.load_model(path)
    # the deserialized optimizer must still be distributed (the reference
    # load_model guarantee, tensorflow/keras/__init__.py:121-155) and keep
    # its slot state
    assert "apply" in type(loaded.optimizer).__dict__
    assert type(loaded.optimizer).__name__ == "SGD"
    np.testing.assert_allclose(
        np.concatenate([w.ravel() for w in loaded.get_weights()]),
        np.concatenate([w.ravel() for w in model.get_weights()]))
    loaded.fit(X, Y, batch_size=16, epochs=1, verbose=0)


def test_tf_multiprocess_world():
    from test_multiprocess import _run_world

    _run_world("tf", 2, timeout=180.0)


def test_tf_multiprocess_autograd():
    from test_multiprocess import _run_world

    _run_world("tf_grad", 2, timeout=180.0)


def test_tf_keras_multiprocess_fit():
    from test_multiprocess import _run_world

    _run_world("tf_keras", 2, timeout=240.0)
