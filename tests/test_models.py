"""Model zoo: the reference's headline benchmark trio (Inception V3,
ResNet, VGG-16 — ``docs/benchmarks.md:5-6`` of the reference) plus MNIST.

Canonical parameter counts pin the architectures: VGG-16 = 138,357,544
(Simonyan & Zisserman), Inception V3 without the aux head = 23,834,568,
ResNet-50 = 25,557,032 + BN stats.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models import InceptionV3, ResNet50, VGG16


def _n_params(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


@pytest.mark.parametrize("cls,side,expected", [
    (VGG16, 32, None),          # count checked at 224 below; 32 is fast
    (InceptionV3, 299, 23_834_568),
])
def test_forward_shape(cls, side, expected):
    model = cls(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, side, side, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    if expected is not None:
        head = 10 * (2048 + 1)
        full = expected - 1000 * (2048 + 1) + head
        assert _n_params(variables["params"]) == full


def test_vgg16_canonical_param_count():
    model = VGG16(num_classes=1000, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)))
    assert _n_params(variables) == 138_357_544


def test_resnet50_canonical_param_count():
    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)))
    assert _n_params(variables["params"]) == 25_557_032


def test_vgg16_train_step():
    """One SGD step end-to-end (no BatchNorm: the no-batch_stats model path
    the benchmark must also handle)."""
    model = VGG16(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    y = jnp.zeros((2,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x)
    assert "batch_stats" not in variables
    opt = optax.sgd(0.01)
    opt_state = opt.init(variables)

    def loss_fn(v):
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(v, x), y).mean()

    loss, grads = jax.value_and_grad(loss_fn)(variables)
    updates, opt_state = opt.update(grads, opt_state, variables)
    new_vars = optax.apply_updates(variables, updates)
    assert np.isfinite(float(loss))
    assert _n_params(new_vars) == _n_params(variables)
