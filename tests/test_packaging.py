"""Packaging parity (§2.7): the feature-probe build must produce the native
core, honor the env build matrix, and fail fast with actionable messages —
the reference's ``setup.py`` contract (``setup.py:84-141,477-592``)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup_py(*args, env=None):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(
        [sys.executable, "setup.py", *args], cwd=_ROOT,
        capture_output=True, text=True, timeout=300, env=full_env)


def test_probe_finds_flags():
    sys.path.insert(0, _ROOT)
    try:
        import setup as setup_mod
        flags = setup_mod.probe_cxx_flags("g++")
    finally:
        sys.path.remove(_ROOT)
        sys.modules.pop("setup", None)
    assert "-fPIC" in flags
    assert any(f.startswith("-std=") for f in flags)


def test_build_native_command(tmp_path):
    result = _setup_py("build_native")
    assert result.returncode == 0, result.stderr
    assert "built" in result.stdout
    lib = os.path.join(_ROOT, "horovod_tpu", "cc", "build", "libhtpu_core.so")
    assert os.path.exists(lib)


def test_without_native_skips():
    result = _setup_py("build_native",
                       env={"HOROVOD_TPU_WITHOUT_NATIVE": "1"})
    assert result.returncode == 0, result.stderr
    assert "skipping native core" in result.stdout


def test_with_native_failure_is_fatal():
    # A broken compiler must fail the build when native is demanded
    # (HOROVOD_WITH_* semantics) but only warn otherwise.
    env = {"CXX": "definitely-not-a-compiler"}
    soft = _setup_py("build_native", env=env)
    assert soft.returncode == 0
    assert "WARNING: native core unavailable" in soft.stderr
    hard = _setup_py("build_native",
                     env={**env, "HOROVOD_TPU_WITH_NATIVE": "1"})
    assert hard.returncode != 0
