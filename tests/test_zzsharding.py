"""Sharding-plane tests (docs/sharding.md).

The ZeRO-1 tentpole's battery: partitioner pad/ownership math, the
shard-major pack/unpack layout the engine buckets with, mesh-spec
grammar, ShardLeaf localize/expand/adopt lifecycle (including the
elastic N→N-1 repartition a relaunch performs), shard-digest and
canonical-commit world-independence, the reduce-scatter+apply+all-gather
donation HLO audit, and real 2-proc worlds — ZeRO-1 vs replicated
BIT-exactness for SGD/momentum/Adam on both negotiation cores, the int8
codec riding the scatter leg, and the sparse codec composing by staying
off the fused path. Named ``zz`` to sort past the 870 s tier-1
truncation point (ROADMAP operational note).
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.sharding import meshplan, zero1 as z1  # noqa: E402

pytestmark = pytest.mark.sharding


# -- partitioner math ---------------------------------------------------------

def test_shard_len_and_slices_cover_exactly():
    """Every (n, world) cell: equal shard lengths, slices tile the
    PADDED leaf in rank order, and the real (clamped-to-n) coverage is
    exactly [0, n) with no overlap."""
    for n in (1, 2, 5, 8, 16, 1023):
        for world in (1, 2, 3, 4, 7):
            s = z1.shard_len(n, world)
            assert s * world >= n
            covered = 0
            for rank in range(world):
                start, stop = z1.shard_slice(n, world, rank)
                assert (start, stop) == (rank * s, (rank + 1) * s)
                covered += max(0, min(stop, n) - min(start, n))
            assert covered == n
            assert z1.padded_len(n, world) == s * world


def test_shard_len_rejects_bad_world():
    with pytest.raises(ValueError):
        z1.shard_len(8, 0)


def test_payload_elems_sums_padded_leaves():
    assert z1.payload_elems([5, 8, 3], 2) == 3 + 4 + 2


def test_pack_rows_is_shard_major():
    """Row r of the packed bucket is the concatenation of every leaf's
    r-th shard — the layout that makes psum_scatter's chunking BE the
    ownership map."""
    leaves = [np.arange(5, dtype=np.float32),
              np.arange(100, 104, dtype=np.float32)]
    world, sbucket = 2, 8
    rows = z1.pack_rows(leaves, world, sbucket)
    assert rows.shape == (world * sbucket,)
    row0, row1 = rows[:sbucket], rows[sbucket:]
    # leaf0 shards: [0,1,2] / [3,4,pad]; leaf1: [100,101] / [102,103]
    np.testing.assert_array_equal(row0[:3], [0, 1, 2])
    np.testing.assert_array_equal(row0[3:5], [100, 101])
    np.testing.assert_array_equal(row1[:3], [3, 4, 0])
    np.testing.assert_array_equal(row1[3:5], [102, 103])


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(11)
    shapes = [(5,), (2, 3), (7,), (1,)]
    leaves = [rng.randn(*s).astype(np.float32) for s in shapes]
    for world in (1, 2, 3):
        sbucket = sum(z1.shard_len(int(np.prod(s)), world)
                      for s in shapes) + 3  # slack like _next_bucket
        rows = z1.pack_rows(leaves, world, sbucket)
        back = z1.unpack_rows(rows, shapes, world, sbucket)
        for a, b in zip(leaves, back):
            np.testing.assert_array_equal(a, b)


def test_pack_rows_overflow_fails_loudly():
    with pytest.raises(ValueError):
        z1.pack_rows([np.zeros(9, np.float32)], 2, 2)


def test_shard_row_pack_split_roundtrip():
    shards = [np.arange(3, dtype=np.float32),
              np.arange(10, 12, dtype=np.float32)]
    row = z1.pack_shard_row(shards, 8)
    assert row.shape == (8,)
    back = z1.split_shard_row(row, [3, 2])
    np.testing.assert_array_equal(back[0], shards[0])
    np.testing.assert_array_equal(back[1], shards[1])


# -- ShardLeaf lifecycle ------------------------------------------------------

def _fake_gather(world, tree_by_rank):
    """An allgather stand-in: concatenates every rank's same-named shard
    in rank order, the wire contract of ``ops.allgather``."""
    def gather(local, name=None):
        del local
        i = int(name.rsplit(".", 1)[1])
        import jax

        parts = []
        for rank in range(world):
            leaves = jax.tree_util.tree_leaves(
                tree_by_rank[rank], is_leaf=z1.is_shard)
            parts.append(np.asarray(leaves[i].data))
        return np.concatenate(parts)
    return gather


def test_localize_expand_roundtrip_world2():
    rng = np.random.RandomState(5)
    tree = {"m": rng.randn(7).astype(np.float32),
            "v": rng.randn(2, 3).astype(np.float32)}
    world = 2
    locals_ = [z1.localize_tree(tree, world, r) for r in range(world)]
    assert z1.has_shards(locals_[0])
    gather = _fake_gather(world, locals_)
    full = z1.expand_tree(locals_[0], gather, tag="t")
    np.testing.assert_array_equal(full["m"], tree["m"])
    np.testing.assert_array_equal(full["v"], tree["v"])
    assert full["v"].shape == (2, 3) and full["v"].dtype == np.float32


def test_localize_tree_rejects_double_localize():
    tree = {"m": np.arange(4, dtype=np.float32)}
    local = z1.localize_tree(tree, 2, 0)
    with pytest.raises(ValueError):
        z1.localize_tree(local, 2, 0)


def test_shard_leaf_is_opaque_to_pytrees():
    """ShardLeaf is deliberately NOT a registered pytree node: tree ops
    see the whole leaf (fail-loud for byte-level consumers), never a
    silent fragment."""
    import jax

    local = z1.localize_tree({"m": np.arange(4, dtype=np.float32)}, 2, 0)
    leaves = jax.tree_util.tree_leaves(local)
    assert len(leaves) == 1 and z1.is_shard(leaves[0])


def test_adopt_tree_repartitions_n_to_n_minus_1():
    """The elastic resharding acceptance cell, unit form: a canonical
    commit cut for world 2 adopts bit-exactly under world 1 (the N→N-1
    relaunch), and the reshard counter ticks."""
    rng = np.random.RandomState(9)
    tree = {"m": rng.randn(9).astype(np.float32),
            "step": np.int32(7)}
    world = 2
    locals_ = [z1.localize_tree({"m": tree["m"]}, world, r)
               for r in range(world)]
    canonical = {"m": z1.expand_tree(
        locals_[0], _fake_gather(world, locals_), tag="c")["m"],
        "step": tree["step"]}
    np.testing.assert_array_equal(canonical["m"], tree["m"])
    template = {"m": locals_[0]["m"], "step": tree["step"]}
    adopted = z1.adopt_tree(template, canonical, 1, 0)
    assert z1.is_shard(adopted["m"])
    assert adopted["m"].spec.world == 1
    np.testing.assert_array_equal(
        np.asarray(adopted["m"].data)[:9], tree["m"])
    assert adopted["step"] == tree["step"]


def test_adopt_tree_rejects_leaf_count_mismatch():
    template = z1.localize_tree({"m": np.arange(4, dtype=np.float32)},
                                2, 0)
    with pytest.raises(ValueError):
        z1.adopt_tree(template, {"m": np.arange(4), "x": np.arange(2)},
                      2, 0)


def test_resident_bytes_counts_shards_only():
    tree = {"m": np.arange(8, dtype=np.float32)}
    assert z1.resident_bytes(tree) == 32
    local = z1.localize_tree(tree, 2, 0)
    assert z1.resident_bytes(local) == 16


def test_shard_digest_sensitivity():
    tree = {"m": np.arange(8, dtype=np.float32)}
    a = z1.shard_digest(z1.localize_tree(tree, 2, 0))
    b = z1.shard_digest(z1.localize_tree(tree, 2, 1))
    c = z1.shard_digest(z1.localize_tree(tree, 4, 0))
    assert a != b and a != c
    again = z1.shard_digest(z1.localize_tree(tree, 2, 0))
    assert a == again


def test_canonical_commit_digest_is_world_independent():
    """tree_digest(canonical) must not depend on the world that cut the
    shards — the property that lets an N→M relaunch verify the sealed
    commit against the SAME digest the N-world sealed."""
    from horovod_tpu.integrity.consensus import tree_digest

    rng = np.random.RandomState(3)
    tree = {"m": rng.randn(10).astype(np.float32)}
    base = tree_digest(tree)
    for world in (2, 3):
        locals_ = [z1.localize_tree(tree, world, r)
                   for r in range(world)]
        canonical = z1.expand_tree(
            locals_[0], _fake_gather(world, locals_), tag="c")
        assert tree_digest(canonical) == base, world


def test_record_imbalance_balanced_is_one():
    rows = np.ones(8, np.float32)
    # two identical ranks: sum = 2*local -> ratio 1.0
    assert z1.record_imbalance(rows, 2 * rows, 2) == pytest.approx(1.0)
    assert z1.record_imbalance(rows, np.zeros(8, np.float32), 2) is None


# -- mesh grammar -------------------------------------------------------------

def test_parse_mesh_spec_grammar():
    assert meshplan.parse_mesh_spec("batch") == 1
    assert meshplan.parse_mesh_spec("batch,model:4") == 4
    for bad in ("model", "batch,model", "batch,model:0", "batch,model:x",
                "nonsense"):
        with pytest.raises(ValueError, match="HOROVOD_MESH"):
            meshplan.parse_mesh_spec(bad)


def test_plan_divides_or_fails():
    p = meshplan.plan(8, "batch,model:4")
    assert (p.batch, p.model) == (2, 4)
    assert p.flat and p.devices == 8 or p.devices == 8
    with pytest.raises(ValueError):
        meshplan.plan(6, "batch,model:4")
    flat = meshplan.plan(4, "batch")
    assert flat.model == 1 and flat.flat


def test_build_mesh_flat_default():
    """The flat default is byte-identical to no mesh at all: one batch
    axis over every device, model axis size 1."""
    import jax

    n = len(jax.devices())
    mesh = meshplan.build_mesh(meshplan.plan(n, "batch"))
    assert mesh.shape[meshplan.BATCH_AXIS] == n
    assert mesh.shape[meshplan.MODEL_AXIS] == 1
    spec = meshplan.param_sharding(mesh, (4, 6))
    # model axis of size 1: params effectively replicated
    from jax.sharding import NamedSharding

    assert isinstance(spec, NamedSharding)


def test_config_knobs_parse(monkeypatch):
    from horovod_tpu.core.config import Config

    monkeypatch.setenv("HOROVOD_MESH", "batch,model:2")
    monkeypatch.setenv("HOROVOD_ZERO", "1")
    cfg = Config.from_env()
    assert cfg.mesh == "batch,model:2"
    assert cfg.zero1 is True
    monkeypatch.delenv("HOROVOD_MESH")
    monkeypatch.delenv("HOROVOD_ZERO")
    cfg = Config.from_env()
    assert cfg.mesh == "batch" and cfg.zero1 is False


# -- donation HLO audit -------------------------------------------------------

def test_reduce_scatter_apply_donation_hlo():
    """The compiled zero1 flush aliases param and every slot bucket
    (grad stays un-donated: its per-device view and the gathered reduced
    output differ in shape) — f32 and the int8 scatter-leg variant
    alike (the ``reduce_donation_hlo`` precedent)."""
    from horovod_tpu.ops import fused_apply as fa
    from horovod_tpu.ops.xla_plane import XlaDataPlane

    plane = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
    for codec in ("none", "int8"):
        for rule in (fa.ApplyRule("sgd", 0.1), fa.ApplyRule("adam", 1e-3)):
            hlo = plane.reduce_scatter_apply_hlo(
                5000, rule, codec=codec, gate=True, denom=2)
            assert "input_output_alias" in hlo, (codec, rule.kind)
            line = [ln for ln in hlo.splitlines()
                    if "input_output_alias" in ln][0]
            assert line.count("alias)") >= 1 + rule.nslots, \
                (codec, rule.kind, line)


# -- multi-process worlds -----------------------------------------------------

def _world_fn(opts, steps, n_leaves, codec):
    """Per-rank body: ``steps`` apply_steps per optimizer kind with the
    ZeRO-1 arming read from HOROVOD_ZERO; slot shards expand through the
    real negotiated allgather before reporting, so replicated and zero1
    runs return comparable (full) trees."""
    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    coord = os.environ.get("HOROVOD_TEST_JAX_COORD")
    if coord:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coord, num_processes=int(os.environ["HOROVOD_SIZE"]),
            process_id=int(os.environ["HOROVOD_RANK"]))
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops import fused_apply as fa
    from horovod_tpu.ops.compression import Compression
    from horovod_tpu.ops.engine import get_engine
    from horovod_tpu.sharding import zero1 as _z1

    hvd.init()
    rank = hvd.rank()
    out = {"rank": rank}
    comp = Compression.lookup(codec) if codec else None
    makers = {"sgd": lambda: fa.sgd(0.1),
              "momentum": lambda: fa.momentum(0.1, 0.9),
              "adam": lambda: fa.adam(1e-2)}
    for kind in opts:
        tx = hvd.DistributedOptimizer(makers[kind](), compression=comp)
        params = {f"l{i}": (np.arange(8 + i, dtype=np.float32) / 7 - 0.4)
                  for i in range(n_leaves)}
        state = tx.init(params)
        for step in range(steps):
            grads = {f"l{i}": np.full(8 + i,
                                      float((rank + 1) * (i + 1)
                                            * (step + 1)) / 8,
                                      np.float32)
                     for i in range(n_leaves)}
            params, state = hvd.apply_step(tx, grads, state, params)
        slots = state.inner.slots
        if _z1.has_shards(slots):
            slots = tuple(
                _z1.expand_tree(s, hvd.allgather,
                                tag=f"test.expand.{kind}.{k}")
                for k, s in enumerate(slots))
        out[kind] = {
            "params": {k: np.asarray(v).tolist()
                       for k, v in params.items()},
            "slots": [{k: np.asarray(v).tolist() for k, v in s.items()}
                      for s in slots],
            "count": int(state.inner.count),
        }
    out["apply"] = get_engine().apply_stats()
    hvd.shutdown()
    return out


def _run_world(np_, opts=("sgd",), steps=4, n_leaves=3, codec="", **env):
    import socket

    from horovod_tpu.runner import run

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0",
            "HOROVOD_DATA_PLANE": "xla",
            "HOROVOD_TEST_JAX_COORD": coord, **env}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        return run(_world_fn, args=(tuple(opts), steps, n_leaves, codec),
                   np=np_, timeout_s=240.0, start_timeout_s=120.0,
                   use_host_data_plane=False)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _assert_states_equal(a, b, kinds):
    for kind in kinds:
        assert a[kind]["params"] == b[kind]["params"], kind
        assert a[kind]["slots"] == b[kind]["slots"], kind
        assert a[kind]["count"] == b[kind]["count"], kind


def test_mp_zero1_bit_exact_vs_replicated_all_rules():
    """THE acceptance pin: ZeRO-1 sharded apply is BIT-exact against the
    replicated fused path for SGD, momentum, and Adam in a real 2-proc
    world — params AND (expanded) slots. Single-definition update math
    plus 2-term IEEE sums make this exact, not approximate."""
    kinds = ("sgd", "momentum", "adam")
    sharded = _run_world(2, opts=kinds, HOROVOD_ZERO="1",
                         HOROVOD_FUSED_APPLY="1")
    plain = _run_world(2, opts=kinds, HOROVOD_ZERO="0",
                       HOROVOD_FUSED_APPLY="1")
    for rank in range(2):
        _assert_states_equal(sharded[rank], plain[rank], kinds)
    assert sharded[0]["apply"]["exec_zero1"]
    assert sharded[0]["apply"]["zero1_batches"] > 0
    assert not plain[0]["apply"]["exec_zero1"]
    # every rank lands the SAME state — sharding must not fork the world
    _assert_states_equal(sharded[0], sharded[1], kinds)


def test_mp_zero1_bit_exact_on_native_negotiation_core():
    """The native C++ core's wire predates apply fingerprints; zero1
    batches arm fused from rank-side uniformity instead — and stay
    bit-exact against the replicated path on that core too."""
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native core unavailable: {cc.load_error()}")
    sharded = _run_world(2, opts=("adam",), HOROVOD_ZERO="1",
                         HOROVOD_FUSED_APPLY="1",
                         HOROVOD_NATIVE_CORE="1")
    plain = _run_world(2, opts=("adam",), HOROVOD_ZERO="0",
                       HOROVOD_FUSED_APPLY="1",
                       HOROVOD_NATIVE_CORE="1")
    for rank in range(2):
        _assert_states_equal(sharded[rank], plain[rank], ("adam",))
    assert sharded[0]["apply"]["zero1_batches"] > 0


def test_mp_zero1_int8_codec_rides_scatter_leg():
    """EQuARX int8 composes with ZeRO-1 (quantized reduce-scatter, no
    gather leg): the batch still lands on the zero1 path and tracks the
    replicated QUANTIZED wire closely — one quantization error instead
    of two, so close-not-bit-equal is the contract."""
    sharded = _run_world(2, opts=("sgd",), codec="int8",
                         HOROVOD_ZERO="1", HOROVOD_FUSED_APPLY="1")
    plain = _run_world(2, opts=("sgd",), codec="int8",
                       HOROVOD_ZERO="0", HOROVOD_FUSED_APPLY="1")
    assert sharded[0]["apply"]["zero1_batches"] > 0
    for key in sharded[0]["sgd"]["params"]:
        np.testing.assert_allclose(
            np.asarray(sharded[0]["sgd"]["params"][key]),
            np.asarray(plain[0]["sgd"]["params"][key]),
            rtol=0, atol=0.05, err_msg=key)
    # the sharded world itself must still be internally consistent
    _assert_states_equal(sharded[0], sharded[1], ("sgd",))


def test_mp_zero1_sparse_codec_composes_by_degrading():
    """The top-k sparse wire cannot ride a reduce-scatter (selection is
    rank-local); HOROVOD_ZERO=1 + sparse must neither wedge nor
    silently corrupt: the batch takes the non-fused sparse path and the
    zero1 counter stays 0."""
    out = _run_world(2, opts=("sgd",), codec="topk",
                     HOROVOD_ZERO="1", HOROVOD_FUSED_APPLY="1")
    assert out[0]["apply"]["zero1_batches"] == 0
    _assert_states_equal(out[0], out[1], ("sgd",))


def _reshard_world_fn():
    """World-2 body for the elastic restore test: build a sharded State,
    commit, and return the canonical pickled commit + shard digests —
    the driver-side artifacts a relaunch restores from."""
    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.integrity.consensus import tree_digest
    from horovod_tpu.sharding import zero1 as _z1

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    slots = {"m": np.arange(10, dtype=np.float32) * (1.0 + 0.0)}
    state = hvd.elastic.State(
        slots=_z1.localize_tree(slots, size, rank), step=3)
    state.commit()
    canonical = state._canonical_commit()
    out = {
        "rank": rank,
        "canonical_slots": np.asarray(canonical["slots"]["m"]).tolist(),
        "tree_digest": tree_digest(canonical),
        "shard_digest": _z1.shard_digest(state._committed).hex(),
    }
    hvd.shutdown()
    return out


def test_mp_resharding_restore_n_to_n_minus_1():
    """World 2 commits a sharded State; the canonical commit restores
    bit-exactly under world 1 (the N→N-1 relaunch), digest-verified:
    the canonical tree digest equals the plain replicated tree's, and
    per-rank shard digests differ (each rank voted its own slice)."""
    from horovod_tpu.integrity.consensus import tree_digest
    from horovod_tpu.runner import run

    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0"}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        out = run(_reshard_world_fn, np=2, timeout_s=240.0,
                  start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    full = np.arange(10, dtype=np.float32)
    for rank in range(2):
        np.testing.assert_array_equal(
            np.asarray(out[rank]["canonical_slots"]), full)
    # canonical == what a replicated run would commit, digest included
    assert out[0]["tree_digest"] == out[1]["tree_digest"]
    assert out[0]["tree_digest"] == tree_digest(
        {"slots": {"m": full}, "step": 3})
    assert out[0]["shard_digest"] != out[1]["shard_digest"]
    # the N-1 adoption: world 1 re-cuts the canonical commit locally
    template = {"slots": z1.localize_tree({"m": full}, 1, 0), "step": 3}
    adopted = z1.adopt_tree(
        template, {"slots": {"m": full}, "step": 3}, 1, 0)
    np.testing.assert_array_equal(
        np.asarray(adopted["slots"]["m"].data)[:10], full)
