"""True multi-process distributed tests.

The reference's multi-process story is "run the same test file under
``mpirun -np N``" (SURVEY §4). Here the parent plays mpirun: it exports the
launcher env (rank/size/controller port/secret) and spawns real worker
processes that negotiate through the TCP controller and move data through
the host exchange — the CPU-world stand-in for the ICI data plane.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(scenario: str, size: int, timeout: float = 90.0,
               extra_env=None, expected_codes=None, worker: str = None,
               ok_marker: str = "WORKER-OK"):
    """Spawn a world; assert per-rank exit codes (default: everyone exits 0
    and prints WORKER-OK; ``expected_codes={rank: code}`` overrides
    individual ranks, e.g. a deliberately crashing victim). ``worker``
    substitutes another worker script for ``_mp_worker.py`` (the soak
    workers in test_soak.py reuse this harness); its ``ok_marker`` is the
    success line those rank-0-exit workers must print."""
    expected_codes = expected_codes or {}
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(size),
            "HOROVOD_LOCAL_RANK": str(rank),
            "HOROVOD_LOCAL_SIZE": str(size),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HOROVOD_DATA_PLANE": "host",
            "HOROVOD_CYCLE_TIME": "2",
        })
        if extra_env:
            # last so scenarios can override the defaults (e.g. the XLA
            # data-plane runs replace HOROVOD_DATA_PLANE)
            env.update(extra_env)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, worker or _WORKER] +
            ([scenario] if scenario else []),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    results = []
    for rank, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"rank {rank} timed out in scenario {scenario!r}")
        results.append((rank, proc.returncode, out, err))
    for rank, code, out, err in results:
        want = expected_codes.get(rank, 0)
        assert code == want, (
            f"rank {rank} exited {code}, expected {want} in scenario "
            f"{scenario!r}\nstdout:\n{out}\nstderr:\n{err}")
        if want == 0:
            # The default worker prints a rank-qualified "WORKER-OK <rank>";
            # requiring the qualified form means a worker echoing another
            # rank's marker (or a partial world) cannot pass for everyone.
            # Substitute workers (the soak scripts) own their marker text.
            marker = (f"{ok_marker} {rank}" if worker is None
                      and ok_marker == "WORKER-OK" else ok_marker)
            assert marker in out, (rank, marker, out)
    return results


# The eager control plane has two interchangeable implementations — the
# Python ControllerService and the native C++ controller_service.cc — with
# one behavior contract; the core scenario battery runs against both
# (native skips where the core cannot build, like test_native_controller).
from horovod_tpu import cc as _cc  # noqa: E402


# Subprocess/soak-heavy by design: excluded from the quick tier (-m "not soak").
pytestmark = pytest.mark.soak

CONTROLLERS = pytest.mark.parametrize("controller", [
    pytest.param("native", marks=pytest.mark.skipif(
        not _cc.available(), reason=f"native core: {_cc.load_error()}")),
    "python",
])


def _ctrl_env(controller):
    return {"HOROVOD_NATIVE_CONTROLLER":
            "1" if controller == "native" else "0"}


@CONTROLLERS
@pytest.mark.parametrize("size", [2, 4])
def test_mp_allreduce(size, controller):
    _run_world("allreduce", size, extra_env=_ctrl_env(controller))


@pytest.mark.skipif(not _cc.available(),
                    reason="native core not built")
def test_mp_allreduce_eight_ranks_native():
    """Full-stack (engine + controller + host plane) at 8 real processes —
    the controller-scale tests drive 256 threaded clients, but this is the
    largest real-process world the suite runs."""
    _run_world("allreduce", 8, timeout=180.0,
               extra_env=_ctrl_env("native"))


@CONTROLLERS
def test_mp_fused(controller):
    _run_world("fused", 2, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_allgather_ragged(controller):
    _run_world("allgather", 3, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_broadcast(controller):
    _run_world("broadcast", 2, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_mismatch_errors_on_all_ranks(controller):
    _run_world("mismatch", 2, extra_env=_ctrl_env(controller))


def test_mp_broadcast_object():
    _run_world("object", 2)


def test_mp_broadcast_object_edge_cases():
    """broadcast_object edges: None / empty payloads, a blob far above
    the (shrunk) fusion threshold, and exact pickle round-trips on
    non-root ranks."""
    _run_world("object_edge", 3,
               extra_env={"HOROVOD_FUSION_THRESHOLD": "65536"})


@CONTROLLERS
def test_mp_stall_shutdown_deadline_aborts(controller):
    """HOROVOD_STALL_SHUTDOWN_TIME_S on both controller implementations:
    a permanently-absent rank becomes RanksAbortedError on the healthy
    rank (python: coordinator-side escalation; native: the wrapper's
    client-side escalation over the wire's stall warnings)."""
    _run_world("stall_abort", 2, timeout=120.0,
               extra_env={"HOROVOD_STALL_WARNING_TIME": "1",
                          "HOROVOD_STALL_SHUTDOWN_TIME_S": "2",
                          **_ctrl_env(controller)})


def _run_world_xla(scenario: str, size: int, **kw):
    """Same scenarios over the eager XLA data plane: workers form a real
    multi-process JAX world (gloo CPU collectives) and bytes move as
    compiled shard_map collectives instead of numpy-over-TCP — the CPU
    stand-in for the TPU-pod NCCL-analog path (``ops/xla_plane.py``)."""
    coord = f"127.0.0.1:{_free_port()}"
    extra = {"HOROVOD_DATA_PLANE": "xla", "HOROVOD_TEST_JAX_COORD": coord}
    extra.update(kw.pop("extra_env", {}))
    return _run_world(scenario, size, extra_env=extra,
                      timeout=kw.pop("timeout", 180.0), **kw)


@pytest.mark.parametrize(
    "scenario", ["allreduce", "fused", "jax_fused", "allgather", "broadcast",
                 "torch"])
def test_mp_xla_plane(scenario):
    _run_world_xla(scenario, 2)


@CONTROLLERS
def test_mp_torch_unused_params(controller):
    """Force-allreduce of untouched grads (reference
    ``test_force_allreduce``): no deadlock, identical weights after step."""
    _run_world("torch_unused", 2, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_torch_autograd(controller):
    """Collective backward rules across real ranks (reference
    ``test_torch.py:377-428``)."""
    _run_world("torch_grad", 2, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_jax_inputs_host_plane(controller):
    """Device-array submissions on the host data plane: lazy D2H, same
    values, jax type round-trip."""
    _run_world("jax_fused", 2, extra_env=_ctrl_env(controller))


@pytest.mark.parametrize("scenario", ["allgather", "jax_fused"])
def test_mp_xla_plane_three_ranks(scenario):
    """Odd-sized world over the device plane: ragged gathers and the
    on-chip fused path must not assume power-of-two rank counts."""
    _run_world_xla(scenario, 3)


@pytest.mark.skipif(not _cc.available(),
                    reason="native core not built")
def test_mp_xla_plane_eight_ranks():
    """The largest real-process device-plane world the suite runs: 8
    gloo-backed processes through the epoll coordinator, watch channels,
    and finalizer completion — the host-plane sibling is
    test_mp_allreduce_eight_ranks_native."""
    _run_world_xla("allreduce", 8, timeout=420.0,
                   extra_env=_ctrl_env("native"))


@CONTROLLERS
def test_mp_autotune_end_to_end(tmp_path, controller):
    """HOROVOD_AUTOTUNE=1 on a real 2-process world: the coordinator's
    tuner must log active-window samples and actually move the knobs
    (reference ``parameter_manager.cc:145-213``), with collectives staying
    correct throughout — on both controller implementations (the native
    service drains its cycle stats to the same GP tuner)."""
    log_path = str(tmp_path / "autotune.csv")
    _run_world("autotune", 2, timeout=180.0,
               extra_env={"HOROVOD_AUTOTUNE": "1",
                          "HOROVOD_AUTOTUNE_LOG": log_path,
                          "HOROVOD_CYCLE_TIME": "1",
                          **_ctrl_env(controller)})
    with open(log_path, encoding="utf-8") as fh:
        lines = [l for l in fh.read().strip().splitlines()
                 if not l.startswith("timestamp")]
    assert len(lines) >= 5, f"too few autotune samples: {lines}"
    knobs = {tuple(l.split(",")[1:3]) for l in lines}
    assert len(knobs) >= 2, f"autotuner never moved the knobs: {knobs}"
    # active-window scoring: no sample may take longer than the test itself
    for line in lines:
        us = float(line.split(",")[4])
        assert us < 60e6, f"implausible active window in sample: {line}"


@CONTROLLERS
def test_mp_peer_death_unblocks_survivors(controller):
    """Kill a rank mid-cycle with fused tensors in flight: every survivor
    must fail its outstanding handles with SHUT_DOWN_ERROR promptly
    (reference ``operations.cc:1942-1957``), not hang until the test
    timeout. The victim exits 3 via os._exit — no shutdown handshake."""
    _run_world("peer_death", 3, expected_codes={2: 3},
               extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_peer_death_xla_plane_unblocks_survivors(controller):
    """The TPU-realistic failure mode: the victim dies at collective
    EXECUTION time, while survivors are blocked inside the compiled XLA
    psum (gloo here, ICI on pods) — a place no poisoned control-plane
    response can reach. The controller's watch channel pushes the abort;
    survivors' engines abandon the stuck collective and surface
    SHUT_DOWN_ERROR within the bound (reference operations.cc:1942-1957)."""
    coord = f"127.0.0.1:{_free_port()}"
    _run_world("peer_death_xla", 3, timeout=120.0,
               expected_codes={2: 3},
               extra_env={"HOROVOD_DATA_PLANE": "xla",
                          "HOROVOD_TEST_JAX_COORD": coord,
                          **_ctrl_env(controller)})


@CONTROLLERS
@pytest.mark.parametrize("scenario", ["subset_02", "subset_12"])
def test_mp_subset_world(scenario, controller):
    """hvd.init(ranks=[...]) on a 3-process world: members communicate in
    list order, non-members get self-worlds, and the controller stays on
    launcher world-rank 0 even when it is not a member (subset_12)."""
    _run_world(scenario, 3, timeout=120.0, extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_local_engine_crash_unblocks_survivors(controller):
    """A local fault that kills only a rank's background engine (process
    still alive, TCP link healthy until the crash-path close) must abort
    the peers like a process death — the crash-path close sends no clean
    detach, so the controller attributes the drop to the rank."""
    _run_world("local_crash", 3, timeout=120.0,
               extra_env=_ctrl_env(controller))


@CONTROLLERS
def test_mp_stall_warning(controller):
    """A rank submitting late must trigger the coordinator's stall warning
    naming the missing rank (``CheckForStalledTensors``), and the collective
    must still complete once the laggard arrives."""
    results = _run_world(
        "stall", 2, timeout=120.0,
        extra_env={"HOROVOD_STALL_WARNING_TIME": "1",
                   "HOROVOD_LOG_LEVEL": "warning",
                   **_ctrl_env(controller)})
    rank0_err = results[0][3]
    assert "Stalled ops: stalled_tensor" in rank0_err
    assert "missing ranks: 1" in rank0_err
