"""Parameter / optimizer-state / object broadcast
(reference: ``test_broadcast_state`` ``test/test_torch.py:802-1003``)."""

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd


def test_broadcast_parameters_identity(hvd):
    params = {"w": jnp.ones((2, 2)), "nested": {"b": np.zeros(3, np.float32)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out["w"]), 1.0)
    np.testing.assert_array_equal(np.asarray(out["nested"]["b"]), 0.0)


def test_broadcast_optimizer_state(hvd):
    params = {"w": jnp.ones((2, 2))}
    opt = optax.adam(1e-3)
    state = opt.init(params)
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    # adam state: (ScaleByAdamState(count, mu, nu), ...) — structure preserved
    import jax

    leaves_in = jax.tree_util.tree_leaves(state)
    leaves_out = jax.tree_util.tree_leaves(out)
    assert len(leaves_in) == len(leaves_out)
    for a, b in zip(leaves_in, leaves_out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_broadcast_optimizer_state_with_scalars(hvd):
    state = {"lr": 0.125, "step": 7, "flag": True, "mu": np.ones(3, np.float32)}
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    assert out["lr"] == 0.125 and isinstance(out["lr"], float)
    assert out["step"] == 7 and isinstance(out["step"], int)
    assert out["flag"] is True
    np.testing.assert_array_equal(out["mu"], 1.0)


def test_broadcast_object(hvd):
    obj = {"config": [1, 2, 3], "name": "resnet50"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_broadcast_object_none_and_empty(hvd):
    # None is a legal payload, not an absence marker
    assert hvd.broadcast_object(None, root_rank=0) is None
    assert hvd.broadcast_object(b"", root_rank=0) == b""
    assert hvd.broadcast_object([], root_rank=0) == []
    assert hvd.broadcast_object({}, root_rank=0) == {}


def test_broadcast_object_large_payload_roundtrips_exactly(hvd):
    # bigger than any fusion window the engine would pick for the wire
    import pickle

    blob = {"blob": bytes(range(256)) * 4096,
            "arr": np.arange(513, dtype=np.float64)}
    out = hvd.broadcast_object(blob, root_rank=0)
    assert pickle.dumps(out) == pickle.dumps(blob)
    # multi-rank versions of these edges run in
    # test_multiprocess.py::test_mp_broadcast_object_edge_cases
