"""Hierarchical negotiation tree (docs/hierarchy.md).

Named ``test_zz*`` past the 870 s tier-1 truncation point on purpose
(the PR 11–17 convention): the planner/merge/expand/fold units are
cheap, but the bit-exactness and degrade worlds each spawn 2-process
runs and the dryrun certification spawns several.

Coverage per the ISSUE-18 battery: the topology planner (flat default,
``auto``/``islands:N`` resolution, degenerate splits degrading to flat,
loud typos), head-side merge eligibility (cache-bit AND, congruent
RequestList merge, every raw fallback: codec / apply-fingerprint /
name / shape / generation divergence and mixed warm-cold cycles),
root-side expansion as the exact inverse (ragged allgather dim0s,
ordinal/digest/shutdown side maps, roster-mismatch refusal), the
per-level consensus fold and flush-ordinal desync texts naming the
ISLAND, the flight-recorder island verdicts, the wire-compat registry
rows, the metrics-summary section, the scaling simulation's sub-linear
root load — and, slow tier, the 2-process worlds: tree bit-exact vs
flat, the native-controller flat degrade, and the full
``dryrun_hierarchy`` certification (head-kill blackbox verdict +
delay-chaos island blame).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import types

import pytest

from horovod_tpu.integrity.consensus import fold_digest
from horovod_tpu.ops.hierarchy import (
    FLAT,
    check_fold,
    expand_submission,
    merge_cycle,
    plan_topology,
)
from horovod_tpu.ops.messages import (
    CacheRequest,
    DataType,
    IslandSubmission,
    Request,
    RequestList,
    RequestType,
)

pytestmark = pytest.mark.hierarchy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- topology planner ----------------------------------------------------------


def test_plan_topology_flat_default_and_degenerate_splits():
    assert plan_topology(8, None) is FLAT
    assert plan_topology(8, "") is FLAT
    assert plan_topology(8, "flat") is FLAT
    # a world of one has nothing to split
    assert plan_topology(1, "islands:4") is FLAT
    # a 1-island tree is the star plus a pointless hop
    assert plan_topology(8, "islands:1") is FLAT
    # auto without a DCN boundary (single host) stays flat
    assert plan_topology(4, "auto", cross_size=1) is FLAT
    assert FLAT.flat and FLAT.n_islands == 0 and FLAT.heads == []


def test_plan_topology_islands_structure():
    topo = plan_topology(8, "islands:2")
    assert not topo.flat and topo.n_islands == 2
    # every rank in exactly one island, island_of the exact inverse
    assert sorted(r for mem in topo.islands.values()
                  for r in mem) == list(range(8))
    for island, members in topo.islands.items():
        assert topo.head_of(island) == min(members)
        for r in members:
            assert topo.island_of[r] == island
    assert topo.heads == [topo.head_of(i) for i in sorted(topo.islands)]
    assert topo.is_head(topo.heads[-1])
    assert not topo.is_head(max(topo.islands[0]))
    # the island count caps at one rank per island
    assert plan_topology(3, "islands:8").n_islands == 3


def test_plan_topology_auto_follows_cross_size():
    topo = plan_topology(8, "auto", cross_size=4)
    assert topo.n_islands == 4
    assert topo.mode == "islands:4"


def test_plan_topology_typos_fail_loudly():
    # a silently-flat "islnds:4" would erase the scaling the knob was
    # set for — every malformed mode must raise, not degrade
    for bad in ("islnds:4", "islands:x", "islands:0", "islands:-2",
                "tree", "auto:2"):
        with pytest.raises(ValueError):
            plan_topology(8, bad)


# -- head-side merge -----------------------------------------------------------


def _req(rank, name, *, shape=(4,), op=RequestType.ALLREDUCE,
         codec="none", fp="", root=-1):
    return Request(request_rank=rank, request_type=op, tensor_name=name,
                   tensor_type=DataType.FLOAT32, tensor_shape=shape,
                   root_rank=root, codec=codec, apply_fingerprint=fp)


def _slot(members, build, **rl_kwargs):
    return {r: RequestList(rank=r, requests=build(r),
                           flush_ordinal=rl_kwargs.get("ordinal", 3))
            for r in members}


def test_merge_congruent_requestlists():
    members = (2, 3)
    slot = _slot(members, lambda r: [_req(r, "grad/w"), _req(r, "grad/b")])
    sub = merge_cycle(1, members, slot)
    assert sub.raw is None and sub.cache is None
    assert [q.tensor_name for q in sub.requests] == ["grad/w", "grad/b"]
    assert all(q.member_ranks == members for q in sub.requests)
    assert sub.member_ordinals == {2: 3, 3: 3}


@pytest.mark.parametrize("deviant", [
    lambda r: [_req(r, "grad/w", codec="fp16" if r == 3 else "none")],
    lambda r: [_req(r, "grad/w", fp="sgd:1" if r == 3 else "")],
    lambda r: [_req(r, "grad/w" if r == 2 else "grad/b")],
    lambda r: [_req(r, "grad/w", shape=(4,) if r == 2 else (8,))],
    lambda r: [_req(r, "grad/w")] * (1 if r == 2 else 2),
    lambda r: [_req(r, "grad/w",
                    op=(RequestType.ALLREDUCE if r == 2
                        else RequestType.BROADCAST), root=0)],
])
def test_merge_divergence_falls_back_to_raw(deviant):
    # codec and apply_fingerprint negotiate per level exactly like
    # dtypes: ANY member deviating makes the cycle merge-ineligible and
    # the root's flat path produces the byte-identical diagnostics
    members = (2, 3)
    slot = _slot(members, deviant)
    sub = merge_cycle(1, members, slot)
    assert sub.raw == slot and sub.requests is None


def test_merge_allgather_records_ragged_dim0s():
    members = (0, 1)
    slot = _slot(members, lambda r: [
        _req(r, "tok", shape=(2 + 3 * r, 5), op=RequestType.ALLGATHER)])
    sub = merge_cycle(0, members, slot)
    assert sub.raw is None
    assert sub.requests[0].gather_dim0s == (2, 5)
    # trailing dims must still agree exactly
    slot = _slot(members, lambda r: [
        _req(r, "tok", shape=(2, 5 + r), op=RequestType.ALLGATHER)])
    assert merge_cycle(0, members, slot).raw is not None


def test_merge_cache_bits_and():
    members = (2, 3)
    slot = {r: CacheRequest(rank=r, bits=b"\xff\x0f", generation=4,
                            flush_ordinal=9) for r in members}
    sub = merge_cycle(1, members, slot)
    assert sub.raw is None and sub.requests is None
    assert sub.cache.bits == b"\xff\x0f" and sub.cache.generation == 4
    assert sub.member_ordinals == {2: 9, 3: 9}


@pytest.mark.parametrize("other", [
    CacheRequest(rank=3, bits=b"\xf0\x0f", generation=4),   # divergent bits
    CacheRequest(rank=3, bits=b"\xff\x0f", generation=5),   # generation desync
    RequestList(rank=3, requests=[_req(3, "grad/w")]),      # mixed warm/cold
])
def test_merge_cache_divergence_falls_back_to_raw(other):
    slot = {2: CacheRequest(rank=2, bits=b"\xff\x0f", generation=4),
            3: other}
    sub = merge_cycle(1, (2, 3), slot)
    assert sub.raw == slot


# -- root-side expansion -------------------------------------------------------


def test_expand_is_the_inverse_of_merge_cold_path():
    members = (2, 3)
    slot = _slot(members, lambda r: [
        _req(r, "grad/w"),
        _req(r, "tok", shape=(1 + r, 3), op=RequestType.ALLGATHER)])
    slot[3].shutdown = True
    slot[2].integrity_digest = [("w", "aa")]
    sub = merge_cycle(1, members, slot)
    assert sub.shutdown_ranks == (3,)
    out = expand_submission(sub)
    assert set(out) == set(members)
    for r in members:
        rl = out[r]
        assert rl.rank == r and rl.flush_ordinal == 3
        assert [q.request_rank for q in rl.requests] == [r, r]
        # the ragged allgather dim0 is restored per member
        assert tuple(rl.requests[1].tensor_shape) == (1 + r, 3)
    assert out[3].shutdown and not out[2].shutdown
    assert out[2].integrity_digest == [("w", "aa")]
    assert out[3].integrity_digest is None


def test_expand_cache_submission_to_per_rank_requests():
    members = (2, 3)
    slot = {r: CacheRequest(rank=r, bits=b"\x0f", generation=7,
                            flush_ordinal=11) for r in members}
    out = expand_submission(merge_cycle(1, members, slot))
    for r in members:
        assert isinstance(out[r], CacheRequest)
        assert out[r].rank == r and out[r].bits == b"\x0f"
        assert out[r].generation == 7 and out[r].flush_ordinal == 11


def test_expand_refuses_malformed_submissions():
    with pytest.raises(ValueError, match="no member ranks"):
        expand_submission(IslandSubmission(island=1, members=()))
    with pytest.raises(ValueError, match="roster"):
        expand_submission(IslandSubmission(
            island=1, members=(2, 3),
            raw={2: RequestList(rank=2), 4: RequestList(rank=4)}))
    with pytest.raises(ValueError, match="neither"):
        expand_submission(IslandSubmission(island=1, members=(2, 3)))


# -- per-level integrity cross-checks ------------------------------------------


def test_check_fold_verifies_the_heads_digest_of_digests():
    digests = {2: [("w", "aa"), ("b", "bb")], 3: None}
    sub = IslandSubmission(island=1, members=(2, 3), requests=[],
                           digests=digests, fold=fold_digest(digests))
    assert check_fold(sub) is None
    sub.fold = "deadbeefdeadbeef"
    err = check_fold(sub)
    assert "island 1 consensus digest fold mismatch" in err
    assert "2, 3" in err
    # nothing digested → nothing to check
    assert check_fold(IslandSubmission(island=1, members=(2,),
                                       requests=[])) is None


def test_island_ordinal_desync_names_the_island():
    from horovod_tpu.ops.controller import ControllerService

    stub = types.SimpleNamespace(
        _lock=threading.Lock(),
        _island_ordinals={"k": {0: 5, 1: 7}},
        _islands={0: (0, 1), 1: (2, 3)})
    with pytest.raises(RuntimeError) as ei:
        ControllerService._check_island_ordinals(stub, "k")
    msg = str(ei.value)
    assert "desync between islands" in msg
    assert "island 1 (ranks 2, 3) at cycle 7" in msg
    # aligned islands (and heads that stamped nothing) pass
    stub._island_ordinals = {"k": {0: 5, 1: 5, 2: None}}
    ControllerService._check_island_ordinals(stub, "k")


def test_flightrec_classifies_island_texts():
    from horovod_tpu.obs.flightrec import classify_incident

    doc = {"reason": "island 1 sub-coordinator (rank 2) exited mid-job; "
                     "its member ranks 2, 3 are unreachable.",
           "ranks": {}}
    assert classify_incident(doc)["verdict"].startswith(
        "island-dead@island1")
    doc = {"reason": "negotiation cycle stream desync between islands: "
                     "island 0 (ranks 0, 1) at cycle 4, island 1 (ranks "
                     "2, 3) at cycle 5 joined one rendezvous",
           "ranks": {}}
    assert classify_incident(doc)["verdict"].startswith("desync: island")
    doc = {"reason": "island 1 consensus digest fold mismatch: head "
                     "stamped aa, root recomputed bb over the windows "
                     "that arrived for ranks 2, 3",
           "ranks": {}}
    assert classify_incident(doc)["verdict"] == "consensus-fold@island1"


# -- registry / tooling rows ---------------------------------------------------


def test_wire_registry_names_every_island_tag_and_field():
    from horovod_tpu.analysis.wire_registry import MESSAGE_FIELDS, RPC_TAGS

    for tag in ("hello_island", "island_cycle", "payload_island",
                "sentry_island", "abort_island"):
        assert tag in RPC_TAGS and RPC_TAGS[tag].strip()
    for field in ("island", "members", "flush_ordinal", "cache",
                  "requests", "raw", "member_ordinals", "digests",
                  "fold", "shutdown_ranks"):
        name = f"IslandSubmission.{field}"
        assert name in MESSAGE_FIELDS and MESSAGE_FIELDS[name].strip()


def test_metrics_summary_renders_hierarchy_section(tmp_path):
    from horovod_tpu.obs.registry import registry
    from horovod_tpu.ops import hierarchy as hier

    hier.HIER_ISLANDS.set(2)
    hier.MERGED_CYCLES.inc()
    hier.ROOT_MESSAGES.inc()
    snap = registry().snapshot()
    assert "horovod_hier_islands" in snap, sorted(snap)
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(snap))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "metrics_summary.py"), str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "hierarchy plane" in proc.stdout
    assert "horovod_hier_merged_cycles_total" in proc.stdout


def test_scaling_simulation_root_load_is_sublinear(tmp_path):
    # small sizes keep this in the quick tier; the acceptance-scale
    # 10^2→10^4 sweep is the bench artifact, not a unit test
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "controller_bench.py"),
         "--scaling", "--scaling-sizes", "16,64", "--scaling-cycles", "1"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["metric"] == "hier_root_message_reduction"
    rows = rec["hierarchy"]["rows"]
    for row in rows:
        assert row["tree_root_msgs"] == row["islands"]
        assert row["tree_root_msgs"] < row["flat_root_msgs"]
        assert row["tree_root_bytes"] < row["flat_root_bytes"]
    # 64 ranks / 8 islands shrinks harder than 16 / 4: sub-linear growth
    assert (rows[1]["flat_root_msgs"] / rows[1]["tree_root_msgs"]
            > rows[0]["flat_root_msgs"] / rows[0]["tree_root_msgs"])
    # and the capture renders through the shared table tool
    (tmp_path / "hier.json").write_text(proc.stdout.splitlines()[-1])
    table = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_table.py"),
         str(tmp_path)], capture_output=True, text=True, timeout=60)
    assert table.returncode == 0, table.stderr
    assert "Negotiation-tree root load" in table.stdout


# -- multi-process worlds (slow tier) ------------------------------------------


def _mp_fn(steps):
    """Per-rank body shipped through runner.run: the three collective
    shapes on both cycle paths, plus the tree counters so a
    silently-flat world cannot pass for a tree one."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    outs = []
    for step in range(steps):
        r = hvd.allreduce(
            np.arange(8, dtype=np.float32) * (rank + 1) + step,
            average=False, name="zzhier.ar")
        g = hvd.allgather(
            np.full((rank + 1, 2), float(rank * 10 + step), np.float32),
            name="zzhier.ag")
        b = hvd.broadcast(
            np.full((3,), float(rank + step), np.float32),
            root_rank=1, name="zzhier.bc")
        outs.append([np.asarray(r).tolist(), np.asarray(g).tolist(),
                     np.asarray(b).tolist()])
    snap = hvd.metrics_snapshot()

    def _val(name):
        samples = (snap.get(name) or {}).get("samples") or []
        return sum(s.get("value", 0) for s in samples)

    hvd.shutdown()
    return {"rank": rank, "outs": outs,
            "hier_islands": _val("horovod_hier_islands"),
            "merged": _val("horovod_hier_merged_cycles_total"),
            "raw": _val("horovod_hier_raw_cycles_total")}


def _world(extra, np_, steps=4):
    from horovod_tpu.runner import run

    env = {"HOROVOD_CYCLE_TIME": "2", "HOROVOD_PLATFORM": "cpu",
           "HOROVOD_CHAOS": "", **extra}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run(_mp_fn, args=(steps,), np=np_, timeout_s=180.0,
                      start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return sorted(results, key=lambda r: r["rank"])


def _expected_outs(np_, steps):
    import numpy as np

    outs = []
    for step in range(steps):
        ar = (np.arange(8, dtype=np.float32)
              * sum(r + 1 for r in range(np_)) + np_ * step)
        ag = np.concatenate([
            np.full((r + 1, 2), float(r * 10 + step), np.float32)
            for r in range(np_)])
        bc = np.full((3,), float(1 + step), np.float32)
        outs.append([ar.tolist(), ag.tolist(), bc.tolist()])
    return outs


@pytest.mark.slow
def test_tree_world_bit_exact_vs_flat():
    flat = _world({"HOROVOD_HIERARCHY": "flat",
                   "HOROVOD_NATIVE_CONTROLLER": "0"}, 2)
    tree = _world({"HOROVOD_HIERARCHY": "islands:2",
                   "HOROVOD_NATIVE_CONTROLLER": "0"}, 2)
    for f, t in zip(flat, tree):
        assert f["outs"] == t["outs"] == _expected_outs(2, 4)
    assert all(r["hier_islands"] == 0 for r in flat)
    assert all(r["hier_islands"] == 2 for r in tree)
    assert sum(r["merged"] for r in tree) > 0
    assert sum(r["raw"] for r in tree) == 0


@pytest.mark.slow
def test_native_controller_degrades_to_flat_with_correct_results():
    # the native wire predates the island RPCs: the tree request must
    # degrade to a WORKING flat world, never a broken tree
    tree = _world({"HOROVOD_HIERARCHY": "islands:2",
                   "HOROVOD_NATIVE_CONTROLLER": "1"}, 2)
    assert all(r["hier_islands"] == 0 for r in tree)
    for r in tree:
        assert r["outs"] == _expected_outs(2, 4)


@pytest.mark.slow
def test_dryrun_hierarchy_certification():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from __graft_entry__ import dryrun_hierarchy

    dryrun_hierarchy()
