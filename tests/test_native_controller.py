"""Native (C++) controller service: parity with the Python service.

The C++ service (``cc/controller_service.cc``) shares the negotiation core
with the Python path but owns its own wire (binary body over the HMAC
framing), rendezvous, and host-plane combine — so those get direct tests:
dtype-exact combine parity against numpy (incl. float16/bfloat16
round-to-nearest-even and bool-or), HMAC interop with hashlib, clean
detach, rank-death abort, and the 32-rank latency bound that motivated the
native implementation (reference: 5 ms cycles at 512 ranks,
``operations.cc:2030``).
"""

from __future__ import annotations

import statistics
import threading
import time

import numpy as np
import pytest

from horovod_tpu import cc
from horovod_tpu.core.config import Config
from horovod_tpu.ops.messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
)
from horovod_tpu.ops.native_controller import (
    NativeControllerClient,
    NativeControllerService,
)
from horovod_tpu.runner.network import WireError

pytestmark = pytest.mark.skipif(not cc.available(),
                                reason=f"native core: {cc.load_error()}")

SECRET = b"n" * 32


def _service(size: int) -> NativeControllerService:
    return NativeControllerService(size, Config.from_env(), secret=SECRET,
                                   port=0)


def _request(rank, name, dtype=DataType.FLOAT32, shape=(16,),
             op=RequestType.ALLREDUCE, root=-1):
    return Request(request_rank=rank, request_type=op, tensor_name=name,
                   tensor_type=dtype, tensor_shape=shape, root_rank=root)


def _world(size, body):
    """Run `body(rank, client)` on `size` threads; re-raise any failure."""
    svc = _service(size)
    errors = []

    def worker(rank):
        try:
            client = NativeControllerClient(("127.0.0.1", svc.port),
                                            secret=SECRET, rank=rank)
            body(rank, client)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    svc.shutdown()
    if errors:
        raise errors[0]


NUMPY_DTYPES = {
    DataType.UINT8: np.uint8, DataType.INT8: np.int8,
    DataType.UINT16: np.uint16, DataType.INT16: np.int16,
    DataType.INT32: np.int32, DataType.INT64: np.int64,
    DataType.FLOAT16: np.float16, DataType.FLOAT32: np.float32,
    DataType.FLOAT64: np.float64, DataType.BOOL: np.bool_,
}


@pytest.mark.parametrize("wire_dtype", sorted(NUMPY_DTYPES, key=int))
def test_combine_matches_numpy(wire_dtype):
    """The C++ allreduce combine must be bit-identical to the Python
    service's numpy sum for every wire dtype — including float16 (numpy
    computes elementwise in f32 and rounds back RNE) and bool (+ is or)."""
    np_dtype = NUMPY_DTYPES[wire_dtype]
    rng = np.random.RandomState(int(wire_dtype))
    if wire_dtype == DataType.BOOL:
        inputs = [rng.rand(64) > 0.5 for _ in range(3)]
    elif np.issubdtype(np_dtype, np.floating):
        inputs = [rng.randn(64).astype(np_dtype) for _ in range(3)]
    else:
        inputs = [rng.randint(0, 50, 64).astype(np_dtype) for _ in range(3)]
    expected = inputs[0].copy()
    for arr in inputs[1:]:
        expected = (expected + arr).astype(np_dtype)
    outs = {}

    def body(rank, client):
        client.cycle(rank, RequestList(rank=rank, requests=[
            _request(rank, "t", dtype=wire_dtype, shape=(64,))]))
        raw = client.payload(rank, 0,
                             np.ascontiguousarray(inputs[rank]).tobytes())
        outs[rank] = np.frombuffer(raw, np_dtype)

    _world(3, body)
    for rank in range(3):
        np.testing.assert_array_equal(outs[rank], expected)


def test_combine_bfloat16_matches_numpy():
    import ml_dtypes

    rng = np.random.RandomState(7)
    inputs = [rng.randn(128).astype(ml_dtypes.bfloat16) for _ in range(3)]
    expected = inputs[0]
    for arr in inputs[1:]:
        expected = (expected + arr).astype(ml_dtypes.bfloat16)
    outs = {}

    def body(rank, client):
        client.cycle(rank, RequestList(rank=rank, requests=[
            _request(rank, "b", dtype=DataType.BFLOAT16, shape=(128,))]))
        raw = client.payload(rank, 0,
                             np.ascontiguousarray(inputs[rank]).tobytes())
        outs[rank] = np.frombuffer(raw, ml_dtypes.bfloat16)

    _world(3, body)
    for rank in range(3):
        np.testing.assert_array_equal(outs[rank].view(np.uint16),
                                      expected.view(np.uint16))


def test_error_strings_match_python_service():
    """Coordinator-constructed errors carry the reference's exact wording
    through the binary wire."""
    seen = {}

    def body(rank, client):
        rl = client.cycle(rank, RequestList(rank=rank, requests=[
            _request(rank, "mismatch", shape=(rank + 2,))]))
        seen[rank] = rl.responses[0]

    _world(2, body)
    for resp in seen.values():
        assert "Mismatched allreduce tensor shapes" in resp.error_message


def test_bad_secret_rejected():
    svc = _service(1)
    with pytest.raises(WireError):
        client = NativeControllerClient(("127.0.0.1", svc.port),
                                        secret=b"wrong" * 8, rank=0,
                                        timeout_s=5.0)
        client.cycle(0, RequestList(rank=0, requests=[]))
    svc.shutdown()


def test_clean_detach_then_new_round():
    """bye + close must not poison the controller (the Python service's
    regression, mirrored here)."""
    svc = _service(2)

    def one_round():
        outs = {}

        def worker(rank):
            c = NativeControllerClient(("127.0.0.1", svc.port),
                                       secret=SECRET, rank=rank)
            outs[rank] = c.cycle(rank, RequestList(rank=rank, requests=[
                _request(rank, "w")]))
            c.close()

        ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        return outs

    assert len(one_round()) == 2
    time.sleep(0.5)  # give the C++ monitor a chance to misfire
    assert len(one_round()) == 2
    svc.shutdown()


def test_rank_death_aborts_waiters():
    """An identified client vanishing without bye must unblock a peer
    parked in the cycle rendezvous with the SHUT_DOWN_ERROR message."""
    svc = _service(2)
    result = {}

    def survivor():
        c = NativeControllerClient(("127.0.0.1", svc.port), secret=SECRET,
                                   rank=0)
        try:
            c.cycle(0, RequestList(rank=0, requests=[_request(0, "x")]))
        except WireError as exc:
            result["err"] = str(exc)
        c.close(detach=False)

    victim = NativeControllerClient(("127.0.0.1", svc.port), secret=SECRET,
                                    rank=1)
    t = threading.Thread(target=survivor)
    t.start()
    time.sleep(0.3)  # survivor parks in the rendezvous
    victim.close(detach=False)  # death, not detach
    t.join(timeout=30)
    svc.shutdown()
    assert "rank 1 exited mid-job" in result.get("err", "")
    assert "shut down" in result["err"]


def test_cycle_latency_bounded_at_32_ranks_native():
    """The reason this service exists: coordinator-side cycle cost in C++.
    Measured ~2 ms median / ~14 ms max on this hardware (vs ~15/38 ms for
    the Python service); bounds leave CI headroom while still asserting
    clearly-better-than-Python behavior."""
    svc = _service(32)
    latencies = []
    errors = []

    def worker(rank):
        try:
            client = NativeControllerClient(("127.0.0.1", svc.port),
                                            secret=SECRET, rank=rank)
            for c in range(30):
                reqs = [_request(rank, f"t{c}_{i}") for i in range(8)]
                t0 = time.perf_counter()
                client.cycle(rank, RequestList(rank=rank, requests=reqs))
                if rank == 0:
                    latencies.append(time.perf_counter() - t0)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    svc.shutdown()
    assert not errors, errors
    median = statistics.median(latencies)
    assert median < 0.1, f"median cycle {median * 1e3:.1f} ms at 32 ranks"
    assert max(latencies) < 0.5, \
        f"worst cycle {max(latencies) * 1e3:.0f} ms at 32 ranks"


def test_native_watch_clean_stop_fires_nothing():
    """Parity with the Python twin: the native service answers parked
    watchers with 'controller stopping' on a clean Stop(), which the
    client maps to a clean termination — no abort callback, and the
    watcher thread returns (vs parking forever / reconnect-looping)."""
    from test_controller_scale import _assert_watch_threads_exit

    svc = _service(2)
    client = NativeControllerClient(("127.0.0.1", svc.port), secret=SECRET,
                                    rank=0)
    fired = threading.Event()
    client.watch(lambda reason: fired.set())
    time.sleep(0.8)  # let the watch request park
    svc.shutdown()
    assert not fired.wait(2.0), "clean stop fired the abort callback"
    _assert_watch_threads_exit()
    client.close()


def test_native_controller_survives_adversarial_connections():
    """Epoll-loop robustness: garbage, oversized length claims, partial
    frames, a parked slow-loris, and rapid anonymous connect/close churn
    (the NIC-probe pattern) must neither crash the coordinator nor abort
    a healthy world sharing it — anonymous connections carry no rank, so
    their disconnects are never rank deaths, and a malformed or
    unauthenticated frame costs exactly that one connection."""
    import socket
    import struct

    svc = _service(2)
    addr = ("127.0.0.1", svc.port)
    held: list = []
    try:
        # 1. oversized length claim (> the 2^31 bound): dropped pre-alloc
        s = socket.create_connection(addr)
        held.append(s)
        s.sendall(b"\x00" * 32 + struct.pack(">Q", 1 << 40) + b"x" * 64)
        # 2. plausible length, garbage HMAC: dropped at authentication
        s2 = socket.create_connection(addr)
        held.append(s2)
        s2.sendall(b"\xab" * 32 + struct.pack(">Q", 16) + b"y" * 16)
        # 3. partial frame then abrupt close
        s3 = socket.create_connection(addr)
        s3.sendall(b"\x01\x02\x03")
        s3.close()
        # 4. slow loris: a valid-looking header prefix, then silence — the
        #    parked fd must not block the event loop for everyone else
        s4 = socket.create_connection(addr)
        held.append(s4)
        s4.sendall(b"\x00" * 20)
        # 5. connect/close churn (anonymous probes)
        for _ in range(50):
            socket.create_connection(addr).close()

        # a healthy 2-rank world on the SAME (attacked) coordinator must
        # still negotiate — clients connect to svc.port, not a fresh one
        outs = {}
        errors = []

        def worker(rank):
            try:
                client = NativeControllerClient(addr, secret=SECRET,
                                                rank=rank)
                out = client.cycle(rank, RequestList(
                    rank=rank, requests=[_request(rank, "adv.t")]))
                outs[rank] = [n for r in out.responses
                              for n in r.tensor_names]
                client.close()
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert outs == {0: ["adv.t"], 1: ["adv.t"]}
    finally:
        for sock in held:
            try:
                sock.close()
            except OSError:
                pass
        svc.shutdown()


def test_native_reconnect_supersedes_old_connection():
    """Parity with the Python twin: a reconnecting rank's stale
    connection close is not a rank death; the world still cycles."""
    svc = _service(2)
    addr = ("127.0.0.1", svc.port)
    c1 = NativeControllerClient(addr, secret=SECRET, rank=0)
    c2 = NativeControllerClient(addr, secret=SECRET, rank=0)  # supersedes
    c1._client.close()  # abrupt, no bye
    time.sleep(0.5)
    outs = {}
    errors = []

    def rank1():
        try:
            c = NativeControllerClient(addr, secret=SECRET, rank=1)
            outs[1] = c.cycle(1, RequestList(
                rank=1, requests=[_request(1, "sup.t")]))
            c.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    t = threading.Thread(target=rank1)
    t.start()
    outs[0] = c2.cycle(0, RequestList(rank=0,
                                      requests=[_request(0, "sup.t")]))
    t.join(timeout=30)
    c2.close()
    svc.shutdown()
    assert not errors, errors
    for out in outs.values():
        assert [n for r in out.responses for n in r.tensor_names] == \
            ["sup.t"]


def test_hello_after_world_shutdown_refused_retryably():
    """A next-world client reaching the DYING service on a re-used port
    must get the retryable CONTROLLER_RESTARTING refusal, not a served
    hello whose first cycle EOFs at stop (re-init soak finding); and its
    connect+hello loop must then reach a successor service. The refusal
    text is an exact contract between both services and both clients."""
    from horovod_tpu.core.status import CONTROLLER_RESTARTING
    from horovod_tpu.ops.controller import connect_with_hello
    from horovod_tpu.ops.native_controller import (
        _decode_status,
        encode_hello,
    )

    svc = _service(2)
    try:
        def body(rank, client):
            client.cycle(rank, RequestList(rank=rank, requests=[],
                                           shutdown=True))

        threads = [threading.Thread(target=lambda r=r: body(
            r, NativeControllerClient(("127.0.0.1", svc.port), secret=SECRET,
                                      rank=r))) for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert svc.wait_world_shutdown(10.0)

        # the world negotiated shutdown; a fresh hello must be refused
        # with the exact sentinel (raw wire client: no retry loop)
        from horovod_tpu.runner.network import BasicClient as _BC
        raw = _BC(("127.0.0.1", svc.port), secret=SECRET, timeout_s=10.0,
                  attempts=1)
        with pytest.raises(WireError) as excinfo:
            try:
                _decode_status(raw.request_raw(encode_hello(0)))
            finally:
                raw.close()
        assert CONTROLLER_RESTARTING in str(excinfo.value)
        port = svc.port
    finally:
        svc.shutdown()

    # ...but connect_with_hello re-dials through it and reaches the
    # successor service once it binds the port
    successor = NativeControllerService(2, Config.from_env(), secret=SECRET,
                                        port=port)
    try:
        client = connect_with_hello(
            ("127.0.0.1", port), SECRET, timeout_s=10.0, connect_attempts=3,
            hello=lambda c: _decode_status(c.request_raw(encode_hello(0))))
        client.close()
    finally:
        successor.shutdown()


def test_python_service_hello_refusal_matches_native():
    """Same contract on the Python service: identical sentinel text,
    identical retry semantics (behavior parity across controllers)."""
    from horovod_tpu.core.status import CONTROLLER_RESTARTING
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        Negotiator,
    )
    from horovod_tpu.runner.network import BasicClient
    from horovod_tpu.ops.messages import RequestList as _RL

    svc = ControllerService(2, Negotiator(2, 1 << 26), secret=SECRET,
                            port=0)
    try:
        def body(rank):
            client = ControllerClient(("127.0.0.1", svc.port), secret=SECRET,
                                      rank=rank)
            client.cycle(rank, _RL(rank=rank, requests=[], shutdown=True))

        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert svc.wait_world_shutdown(10.0)

        # raw (no-retry-through) check: the refusal carries the sentinel
        with pytest.raises(WireError) as excinfo:
            client = BasicClient(("127.0.0.1", svc.port), secret=SECRET,
                                 timeout_s=10.0, attempts=1)
            try:
                client.request(("hello", 0))
            finally:
                client.close()
        assert CONTROLLER_RESTARTING in str(excinfo.value)
    finally:
        svc.shutdown()


def test_world_mismatch_refusal_text_parity():
    """Both services must emit the EXACT world_mismatch_error() text for a
    wrong-world hello — the substring is what both clients' retry checks
    key on, and the full text is the cross-controller contract."""
    from horovod_tpu.core.status import WORLD_MISMATCH
    from horovod_tpu.ops.controller import (
        ControllerService,
        Negotiator,
        world_mismatch_error,
    )
    from horovod_tpu.ops.native_controller import (
        _decode_status,
        encode_hello,
    )
    from horovod_tpu.runner.network import BasicClient

    expected = world_mismatch_error("sub:0,1", "sub:9")
    assert WORLD_MISMATCH in expected

    svc = NativeControllerService(2, Config.from_env(), secret=SECRET,
                                  port=0, world_id="sub:0,1")
    try:
        raw = BasicClient(("127.0.0.1", svc.port), secret=SECRET,
                          timeout_s=10.0, attempts=1)
        with pytest.raises(WireError) as excinfo:
            try:
                _decode_status(raw.request_raw(encode_hello(0, "sub:9")))
            finally:
                raw.close()
        assert expected in str(excinfo.value)
    finally:
        svc.shutdown()

    psvc = ControllerService(2, Negotiator(2, 1 << 26), secret=SECRET,
                             port=0, world_id="sub:0,1")
    try:
        raw = BasicClient(("127.0.0.1", psvc.port), secret=SECRET,
                          timeout_s=10.0, attempts=1)
        with pytest.raises(WireError) as excinfo:
            try:
                raw.request(("hello", 0, "sub:9"))
            finally:
                raw.close()
        assert expected in str(excinfo.value)
    finally:
        psvc.shutdown()
