"""Haiku front-end shim (backend-binding parity; reference
``horovod/keras/__init__.py`` + ``horovod/tensorflow/keras/__init__.py``
both binding ``horovod/_keras``)."""

import haiku as hk
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg
import horovod_tpu.haiku as hvd_hk
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def _net_fn(x):
    return hk.Linear(2, w_init=hk.initializers.Constant(1.0))(x)


def _make():
    net = hk.without_apply_rng(hk.transform(_net_fn))
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    tx = hvd_hk.create_distributed_optimizer(optax.sgd(0.5))
    return net, tx, hvd_hk.TrainingState.create(params, tx)


def test_training_state_step_matches_sgd(hvd):
    """Size-1 world: a step through the wrapped optimizer matches sgd."""
    net, tx, state = _make()
    x = jnp.ones((2, 4))

    def loss_fn(p):
        return jnp.sum(net.apply(p, x) ** 2)

    grads = jax.grad(loss_fn)(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    ref = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g,
                                 state.params, grads)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-6),
        new_params, ref)
    assert opt_state is not None


def test_spmd_averaging(hvd):
    """Per-shard grads differ; the update must use the mean."""
    mesh = data_parallel_mesh()
    tx = hvd_hk.create_distributed_optimizer(optax.sgd(1.0),
                                             axis_name=DATA_AXIS)
    gs = jnp.arange(8.0, dtype=jnp.float32)

    def step(g):
        params = jnp.zeros(())
        s = tx.init(params)
        u, _ = tx.update(g[0], s, params)
        return u

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P()))(gs)
    np.testing.assert_allclose(np.asarray(out), -3.5)


def test_broadcast_and_checkpoint_roundtrip(hvd, tmp_path):
    """broadcast + save/load of the (params, net_state, opt_state) triple."""
    net, tx, state = _make()
    state = hvd_hk.broadcast_training_state(state)
    path = str(tmp_path / "hk_ckpt")
    hvd_hk.save_model(path, state)
    _, _, template = _make()
    restored = hvd_hk.load_model(path, template)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        state.params, restored.params)
    assert restored.net_state is None
    assert isinstance(restored, hvd_hk.TrainingState)


def test_package_export():
    assert hvd_pkg.haiku is hvd_hk
