"""Distributed tracing + straggler attribution (docs/tracing.md).

Tiers in this module:

* unit — rank-suffixed timeline paths, span stamps, metadata records,
  min-RTT clock-sync math against a skewed stub service, the
  coordinator's arrival attribution, report folding, trace_merge
  validation/correction;
* multi-process — the acceptance criterion: a 2-proc world's per-rank
  trace files merge into one valid clock-corrected Chrome trace with a
  lane per rank and monotone nesting, and a chaos ``delay@rank1``
  injection flips ``straggler_report``'s verdict to rank 1 while the
  clean run names no dominant rank (mirrors
  ``__graft_entry__.dryrun_tracing``);
* ``slow`` — bigger-world soak variants.

Named test_tracing.py so it sorts after the tier-1 870 s truncation
point (ROADMAP operational note), like test_metrics.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.obs.registry import registry as _registry
from horovod_tpu.obs.tracing import (
    FAMILY_BLAME_S,
    FAMILY_LAST,
    FAMILY_SPREAD,
    GAUGE_OFFSET,
    GAUGE_RTT,
    ClockSync,
    build_straggler_report,
    set_reference_clock,
)
from horovod_tpu.utils.timeline import (
    CLOCK_SYNC,
    TRACE_META,
    Timeline,
    rank_timeline_path,
)

pytestmark = pytest.mark.tracing

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SECRET = b"s" * 32


def _load_trace_merge():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_trace_merge_under_test",
        os.path.join(_ROOT, "tools", "trace_merge.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- timeline units ------------------------------------------------------------


def test_rank_timeline_path_suffix_scheme():
    assert rank_timeline_path("/tmp/t.json", 3) == "/tmp/t.rank3.json"
    assert rank_timeline_path("/tmp/trace", 0) == "/tmp/trace.rank0"


def test_timeline_span_stamps_and_meta_records(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")  # inspectable writer
    path = tmp_path / "t.json"
    tl = Timeline(str(path))
    tl.meta(TRACE_META, {"rank": 2, "size": 4, "epoch": 0})
    tl.negotiate_start("g", "allreduce")
    tl.negotiate_end("g", args={"cycle": 7, "cache_generation": 3})
    tl.start("g", "allreduce", args={"cycle": 7})
    tl.end("g", shape=(4,))
    tl.meta(CLOCK_SYNC, {"offset_us": -12.5, "rtt_us": 80.0, "rank": 2})
    tl.close()
    records = [r for r in json.loads(path.read_text()) if r]
    metas = {r["name"]: r["args"] for r in records if r.get("ph") == "M"
             and r["name"] in (TRACE_META, CLOCK_SYNC)}
    assert metas[TRACE_META]["rank"] == 2
    assert metas[CLOCK_SYNC]["offset_us"] == -12.5
    ends = [r for r in records if r.get("ph") == "E"]
    assert {"cycle": 7, "cache_generation": 3} in [
        r.get("args") for r in ends]
    begins = [r for r in records if r.get("ph") == "B" and
              r.get("name") == "ALLREDUCE"]
    assert begins and begins[0]["args"] == {"cycle": 7}


# -- clock sync ----------------------------------------------------------------


SKEW_US = 123456.0


def _skewed_clock_service(delay_pattern):
    """A stub controller whose clock runs SKEW_US ahead; probes are
    answered after ``delay_pattern[i % len]`` seconds of (asymmetric)
    response queueing — what min-RTT filtering exists to reject."""
    from horovod_tpu.runner.network import BasicService

    calls = {"n": 0}

    def handle(req, _sock):
        assert req[0] == "clock_probe", req
        delay = delay_pattern[calls["n"] % len(delay_pattern)]
        calls["n"] += 1
        if delay:
            time.sleep(delay)
        return ("clock", time.monotonic_ns() / 1e3 + SKEW_US)

    return BasicService("fake-clock", handle, secret=SECRET, port=0)


def test_clock_sync_min_rtt_filter_rejects_queueing(tmp_path, monkeypatch):
    """All but one probe suffer 30 ms of one-sided delay (midpoint error
    ~15 ms); the estimate must come from the one clean probe — within a
    couple ms of the true skew, an order of magnitude tighter than the
    corrupted samples."""
    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")
    svc = _skewed_clock_service([0.03, 0.03, 0.0, 0.03])
    tl = Timeline(str(tmp_path / "t.json"))
    try:
        sync = ClockSync(("127.0.0.1", svc.port), SECRET, rank=1,
                         timeline=tl, probes=4, interval_s=0)
        result = sync.sync_once()
        assert result is not None
        offset_us, rtt_us = result
        assert abs(offset_us - SKEW_US) < 5000.0, offset_us
        assert rtt_us < 15000.0  # the filter picked the clean probe
        # a mean over the battery would sit ~15 ms off; prove we beat it
        assert abs(offset_us - SKEW_US) < 10000.0
        snap = _registry().snapshot()
        assert snap[GAUGE_OFFSET]["samples"][0]["value"] == \
            pytest.approx(offset_us, abs=1.0)
        assert snap[GAUGE_RTT]["samples"][0]["value"] > 0
    finally:
        tl.close()
        svc.shutdown()
    records = [r for r in json.loads((tmp_path / "t.json").read_text())
               if r and r.get("name") == CLOCK_SYNC]
    assert records and records[0]["args"]["rank"] == 1
    assert abs(records[0]["args"]["offset_us"] - SKEW_US) < 5000.0


def test_clock_sync_failure_drops_battery_and_degrades():
    sync = ClockSync(("127.0.0.1", 1), SECRET, rank=1, probes=2,
                     interval_s=0)
    assert sync.sync_once() is None
    assert sync.offset_us is None


def test_set_reference_clock_zero_offset(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")
    tl = Timeline(str(tmp_path / "t.json"))
    set_reference_clock(0, tl)
    tl.close()
    snap = _registry().snapshot()
    assert snap[GAUGE_OFFSET]["samples"][0]["value"] == 0
    records = [r for r in json.loads((tmp_path / "t.json").read_text())
               if r and r.get("name") == CLOCK_SYNC]
    assert records[0]["args"] == {"offset_us": 0.0, "rtt_us": 0.0,
                                 "rank": 0}


# -- coordinator attribution ---------------------------------------------------


def _labeled_value(snap, family, rank) -> float:
    fam = snap.get(family)
    if not fam:
        return 0.0
    for sample in fam["samples"]:
        if sample["labels"].get("rank") == str(rank):
            return sample["value"]
    return 0.0


def test_coordinator_charges_last_arriver():
    """Rank 1 submits each cycle ~25 ms late: the blame counters must
    charge rank 1 (by count AND seconds) and the spread histogram must
    see the delays. Deltas against the process-global registry — other
    tests share it."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.ops.messages import (
        DataType,
        Request,
        RequestList,
        RequestType,
    )

    before = _registry().snapshot()
    cycles = 6
    service = ControllerService(
        2, make_negotiator(2, Config.from_env()), secret=SECRET, port=0)
    errors: list = []

    def worker(rank: int) -> None:
        try:
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET)
            for c in range(cycles):
                if rank == 1:
                    time.sleep(0.025)
                client.cycle(rank, RequestList(rank=rank, requests=[
                    Request(request_rank=rank,
                            request_type=RequestType.ALLREDUCE,
                            tensor_name=f"t{c}",
                            tensor_type=DataType.FLOAT32,
                            tensor_shape=(4,), root_rank=-1)]))
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    service.shutdown()
    assert not errors, errors
    after = _registry().snapshot()
    blamed_1 = _labeled_value(after, FAMILY_LAST, 1) - \
        _labeled_value(before, FAMILY_LAST, 1)
    blamed_0 = _labeled_value(after, FAMILY_LAST, 0) - \
        _labeled_value(before, FAMILY_LAST, 0)
    assert blamed_1 >= cycles - 1, (blamed_0, blamed_1)
    seconds_1 = _labeled_value(after, FAMILY_BLAME_S, 1) - \
        _labeled_value(before, FAMILY_BLAME_S, 1)
    assert seconds_1 >= 0.02 * (cycles - 1), seconds_1
    spread_count = after[FAMILY_SPREAD]["samples"][0]["count"] - \
        (before.get(FAMILY_SPREAD, {"samples": [{"count": 0}]})
         ["samples"][0]["count"])
    assert spread_count >= cycles


def test_clock_probe_rpc_and_world_gate():
    """The probe answers with the service host's monotonic µs on an
    anonymous connection; a different world's probe is refused like
    hello/watch."""
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerService,
        make_negotiator,
    )
    from horovod_tpu.runner.network import BasicClient, WireError

    service = ControllerService(
        1, make_negotiator(1, Config.from_env()), secret=SECRET, port=0,
        world_id="full:1")
    client = BasicClient(("127.0.0.1", service.port), secret=SECRET,
                         timeout_s=5.0)
    try:
        (kind, server_us), t0, t1 = client.rtt_probe(
            ("clock_probe", 0, "full:1"))
        assert kind == "clock"
        # same host, same clock: the answer sits inside the probe window
        assert t0 * 1e6 <= server_us <= t1 * 1e6
        with pytest.raises(WireError, match="different world"):
            client.rtt_probe(("clock_probe", 0, "sub:7,9"))
    finally:
        client.close()
        service.shutdown()


# -- report folding ------------------------------------------------------------


def _labeled_counter_family(values):
    return {"type": "counter", "help": "", "label_names": ["rank"],
            "samples": [{"value": v, "labels": {"rank": str(r)}}
                        for r, v in values.items()]}


def _spread_family(bounds, buckets, total_s, count):
    return {"type": "histogram", "help": "", "label_names": [],
            "samples": [{"bounds": bounds, "buckets": buckets,
                         "sum": total_s, "count": count, "labels": {}}]}


def _wait_family(total_s, count):
    return {"type": "histogram", "help": "", "label_names": [],
            "samples": [{"bounds": [1.0], "buckets": [count, 0],
                         "sum": total_s, "count": count, "labels": {}}]}


def test_build_report_blame_shares_and_dominance_gating():
    coord = {
        FAMILY_LAST: _labeled_counter_family({1: 8, 0: 2}),
        FAMILY_BLAME_S: _labeled_counter_family({1: 0.40, 0: 0.01}),
        FAMILY_SPREAD: _spread_family([0.01, 0.1], [2, 8, 0], 0.41, 10),
        "horovod_negotiation_cycle_seconds": _wait_family(1.2, 10),
        "horovod_execute_seconds": _wait_family(0.3, 10),
    }
    report = build_straggler_report({0: coord, 1: {
        "horovod_negotiation_cycle_seconds": _wait_family(0.9, 10)}})
    assert not report["degraded"]
    assert report["cycles_attributed"] == 10
    assert report["blame"][1]["blame_share"] == pytest.approx(0.40 / 0.41)
    assert report["blame"][1]["cycle_share"] == pytest.approx(0.8)
    assert report["dominant_rank"] == 1  # mean 41 ms >> 5 ms floor
    assert report["per_rank"][0]["negotiation_wait_s"] == 1.2
    assert report["per_rank"][0]["execute_s"] == 0.3
    assert report["per_rank"][1]["negotiation_wait_s"] == 0.9

    # same shares, sub-floor spreads: scheduler jitter names NO straggler
    quiet = dict(coord)
    quiet[FAMILY_BLAME_S] = _labeled_counter_family({1: 0.008, 0: 0.002})
    quiet[FAMILY_SPREAD] = _spread_family([0.01, 0.1], [10, 0, 0],
                                          0.010, 10)
    report = build_straggler_report({0: quiet})
    assert report["dominant_rank"] is None

    # majority gate: 50/50 blame must not name a scapegoat
    split = dict(coord)
    split[FAMILY_BLAME_S] = _labeled_counter_family({1: 0.2, 0: 0.2})
    report = build_straggler_report({0: split})
    assert report["dominant_rank"] is None


def test_report_fold_loads_without_the_package():
    """tools/straggler_report.py analyzes snapshots on machines without
    the training environment by exec'ing obs/tracing.py directly when
    ``import horovod_tpu`` (jax) is unavailable — which only works while
    that module's top level stays stdlib-only. Load it standalone and
    run the fold."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_standalone_fold", os.path.join(
            _ROOT, "horovod_tpu", "obs", "tracing.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # raises if a package import crept in
    report = mod.build_straggler_report({0: {
        FAMILY_LAST: _labeled_counter_family({1: 9, 0: 1}),
        FAMILY_BLAME_S: _labeled_counter_family({1: 0.5, 0: 0.01}),
        FAMILY_SPREAD: _spread_family([0.1], [10, 0], 0.51, 10),
    }})
    assert report["dominant_rank"] == 1


def test_build_report_degraded_without_attribution_families():
    report = build_straggler_report({1: {
        "horovod_negotiation_cycle_seconds": _wait_family(0.9, 10)}})
    assert report["degraded"] and report["dominant_rank"] is None
    assert report["per_rank"][1]["negotiation_cycles"] == 10


# -- trace merge ---------------------------------------------------------------


def _rank_trace(path, rank, offset_us, spans, extra=()):
    """Synthesize one per-rank timeline file: meta records + B/E spans
    at LOCAL timestamps (ts_rank0 = ts_local + offset_us)."""
    records = [
        {"name": TRACE_META, "ph": "M", "pid": 0, "tid": 0,
         "args": {"rank": rank, "size": 2, "epoch": 0}},
        {"name": CLOCK_SYNC, "ph": "M", "pid": 0, "tid": 0,
         "args": {"offset_us": offset_us, "rtt_us": 100.0, "rank": rank}},
        # a worse (higher-RTT) estimate that must NOT win the correction
        {"name": CLOCK_SYNC, "ph": "M", "pid": 0, "tid": 0,
         "args": {"offset_us": offset_us + 9999.0, "rtt_us": 5000.0,
                  "rank": rank}},
    ]
    for name, begin, end in spans:
        records.append({"name": name, "ph": "B", "pid": 0, "tid": 1,
                        "ts": begin})
        records.append({"ph": "E", "pid": 0, "tid": 1, "ts": end,
                        "args": {"cycle": 0}})
    records.extend(extra)
    path.write_text(json.dumps(records))
    return path


def test_trace_merge_corrects_onto_rank0_timebase(tmp_path):
    merge = _load_trace_merge()
    p0 = _rank_trace(tmp_path / "t.rank0.json", 0, 0.0,
                     [("NEGOTIATE_ALLREDUCE", 1000.0, 1500.0)])
    p1 = _rank_trace(tmp_path / "t.rank1.json", 1, -250.0,
                     [("NEGOTIATE_ALLREDUCE", 1250.0, 1750.0)])
    out = str(tmp_path / "merged.json")
    summary = merge.merge([str(p0), str(p1)], out)
    assert summary["ranks"] == 2
    records = json.loads(open(out).read())
    assert {r["pid"] for r in records} == {0, 1}
    lanes = {r["pid"]: r["args"]["name"] for r in records
             if r.get("name") == "process_name"}
    assert lanes[0].startswith("rank 0") and lanes[1].startswith("rank 1")
    b1 = [r for r in records if r["pid"] == 1 and r.get("ph") == "B"][0]
    assert b1["ts"] == pytest.approx(1000.0)  # min-RTT offset applied
    b0 = [r for r in records if r["pid"] == 0 and r.get("ph") == "B"][0]
    assert b0["ts"] == pytest.approx(1000.0)


def test_trace_merge_rejects_corrupt_nesting(tmp_path):
    merge = _load_trace_merge()
    good = _rank_trace(tmp_path / "t.rank0.json", 0, 0.0,
                       [("X", 10.0, 20.0)])
    orphan_end = _rank_trace(
        tmp_path / "t.rank1.json", 1, 0.0, [],
        extra=[{"ph": "E", "pid": 0, "tid": 2, "ts": 5.0}])
    with pytest.raises(ValueError, match="without a matching B"):
        merge.merge([str(good), str(orphan_end)],
                    str(tmp_path / "m.json"))
    backwards = _rank_trace(tmp_path / "t.rank2.json", 2, 0.0, [],
                            extra=[{"name": "X", "ph": "B", "pid": 0,
                                    "tid": 2, "ts": 50.0},
                                   {"ph": "E", "pid": 0, "tid": 2,
                                    "ts": 10.0}])
    with pytest.raises(ValueError, match="backwards"):
        merge.merge([str(good), str(backwards)],
                    str(tmp_path / "m.json"))
    dup = _rank_trace(tmp_path / "dup.rank0.json", 0, 0.0,
                      [("X", 1.0, 2.0)])
    with pytest.raises(ValueError, match="duplicate rank"):
        merge.merge([str(good), str(dup)], str(tmp_path / "m.json"))


def test_trace_merge_unsynced_lane_keeps_local_timebase(tmp_path):
    merge = _load_trace_merge()
    records = [
        {"name": TRACE_META, "ph": "M", "pid": 0, "tid": 0,
         "args": {"rank": 0, "size": 1, "epoch": 0}},
        {"name": "X", "ph": "B", "pid": 0, "tid": 1, "ts": 7.0},
        {"ph": "E", "pid": 0, "tid": 1, "ts": 9.0},
    ]
    p = tmp_path / "t.rank0.json"
    p.write_text(json.dumps(records))
    summary = merge.merge([str(p)], str(tmp_path / "m.json"))
    assert summary["corrected"] == 0  # no CLOCK_SYNC: left untouched
    assert summary["unsynced_ranks"] == [0]  # and the summary SAYS so
    out = json.loads((tmp_path / "m.json").read_text())
    assert [r["ts"] for r in out if r.get("ph") in "BE"] == [7.0, 9.0]


def test_trace_merge_cli_contract(tmp_path):
    _rank_trace(tmp_path / "t.rank0.json", 0, 0.0, [("X", 1.0, 2.0)])
    _rank_trace(tmp_path / "t.rank1.json", 1, 10.0, [("X", 1.5, 2.5)])
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "trace_merge.py"),
         str(tmp_path / "t.json")],  # base path expands to the family
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr
    summary = json.loads(result.stdout.strip().splitlines()[-1])
    assert summary["ranks"] == 2
    assert os.path.exists(summary["out"])


def test_straggler_report_cli_contract(tmp_path):
    doc = {"world": {}, "ranks": {"0": {
        FAMILY_LAST: _labeled_counter_family({1: 9, 0: 1}),
        FAMILY_BLAME_S: _labeled_counter_family({1: 0.5, 0: 0.01}),
        FAMILY_SPREAD: _spread_family([0.1], [10, 0], 0.51, 10),
    }}}
    snap = tmp_path / "snap.json"
    snap.write_text(json.dumps(doc))
    result = subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "tools", "straggler_report.py"), str(snap)],
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr
    lines = result.stdout.strip().splitlines()
    report = json.loads(lines[-1])
    assert report["dominant_rank"] == 1
    assert "last-arriver blame" in result.stdout


def test_bench_timeline_dir_flag_parses():
    sys.path.insert(0, _ROOT)
    try:
        import bench

        args = bench._parse_args(["--timeline-dir", "/tmp/tdir"])
        assert args.timeline_dir == "/tmp/tdir"
    finally:
        sys.path.remove(_ROOT)


# -- single-process engine integration ----------------------------------------


def test_engine_stamps_cycle_ordinals(tmp_path, monkeypatch):
    """A recording engine attaches cycle ordinals to NEGOTIATE ends and
    EXECUTE begins, and writes the TRACE_META identity record."""
    monkeypatch.setenv("HOROVOD_TIMELINE", str(tmp_path / "t.json"))

    import horovod_tpu as hvd

    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        hvd.allreduce(np.ones((8,), np.float32), name="stamp.a")
        hvd.allreduce(np.ones((8,), np.float32), name="stamp.b")
    finally:
        hvd.shutdown()
    records = [r for r in json.loads((tmp_path / "t.json").read_text())
               if r]
    metas = [r for r in records if r.get("name") == TRACE_META]
    assert metas and metas[0]["args"]["size"] == 1
    stamped_ends = [r["args"]["cycle"] for r in records
                    if r.get("ph") == "E" and "cycle" in r.get("args", {})]
    assert stamped_ends and all(isinstance(c, int) for c in stamped_ends)
    exec_begins = [r for r in records if r.get("ph") == "B" and
                   r.get("name") == "ALLREDUCE"]
    assert exec_begins and all(
        "cycle" in r.get("args", {}) for r in exec_begins)
    # the two allreduces ran on different engine cycles: ordinals move
    assert len(set(stamped_ends)) >= 2


# -- multi-process acceptance --------------------------------------------------


def _tracing_world_fn(steps, min_spread_ms):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    for _ in range(steps):
        out = hvd.allreduce(_np.full((16,), float(rank + 1), _np.float32),
                            average=False, name="trace.t")
        _np.testing.assert_array_equal(
            _np.asarray(out), float(sum(range(1, size + 1))))
    report = None
    if rank == 0:
        report = hvd.straggler_report(min_spread_s=min_spread_ms / 1e3)
    local = hvd.metrics_snapshot()
    hvd.shutdown()
    return {"rank": rank, "report": report,
            "offset": local.get(GAUGE_OFFSET, {"samples": [{}]})
            ["samples"][0].get("value")}


def _run_tracing_world(tmp_path, label, steps=16, chaos="", np_=2):
    from horovod_tpu.runner import run

    base = str(tmp_path / f"{label}.json")
    pins = {"HOROVOD_NATIVE_CONTROLLER": "0",
            "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_PLATFORM": "cpu",
            "HOROVOD_TIMELINE": base,
            "HOROVOD_TIMELINE_ALL_RANKS": "1",
            "HOROVOD_TIMELINE_MARK_CYCLES": "1",
            "HOROVOD_METRICS_INTERVAL_S": "0.3",
            "HOROVOD_CHAOS": chaos}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        results = run(_tracing_world_fn, args=(steps, 5.0), np=np_,
                      timeout_s=180.0, start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    merge = _load_trace_merge()
    paths = merge.expand_inputs([base])
    assert len(paths) == np_, paths
    out = str(tmp_path / f"{label}.merged.json")
    summary = merge.merge(paths, out)
    return results, summary, out


def test_mp_merged_trace_and_straggler_verdicts(tmp_path):
    """The acceptance criterion (ISSUE 6): with a chaos delay on rank
    1's wire the report charges rank 1 the majority of the blame; the
    same world without injection names no dominant rank; and both runs'
    per-rank trace files merge into valid Chrome JSON with one
    clock-corrected lane per rank and monotone nesting (merge() raises
    on any violation)."""
    results, summary, out = _run_tracing_world(
        tmp_path, "chaos", chaos="delay@rank1:40ms:every3")
    report = [r for r in results if r["rank"] == 0][0]["report"]
    assert report["dominant_rank"] == 1, report
    assert report["blame"][1]["blame_share"] > 0.5, report
    assert report["cycles_attributed"] > 0
    assert summary["ranks"] == 2
    merged = json.loads(open(out).read())  # valid JSON by construction
    assert {r["pid"] for r in merged} == {0, 1}
    assert summary["unsynced_ranks"] == []  # EVERY lane carried CLOCK_SYNC
    assert summary["corrected"] > 0
    # rank 1 synced against the coordinator: same host, so the estimated
    # offset is small but PRESENT (the gauge rode the snapshot wire)
    offsets = {r["rank"]: r["offset"] for r in results}
    assert offsets[0] == 0
    assert offsets[1] is not None

    results, summary, _out = _run_tracing_world(tmp_path, "clean")
    report = [r for r in results if r["rank"] == 0][0]["report"]
    assert report["dominant_rank"] is None, report
    assert summary["ranks"] == 2


@pytest.mark.slow
def test_mp_tracing_soak_three_ranks(tmp_path):
    """Bigger world, longer run: attribution still lands on the injected
    straggler and every lane still merges clean. Two sizing rules, both
    learned from observed flakes: (1) the delay must DOMINATE genuine
    scheduler stalls — 3 GIL-bound processes on a small CI box make rank
    0 (which also hosts the controller) a real multi-10ms straggler the
    attribution honestly charges, and a 30 ms injection lost the
    majority vote to that noise; (2) the period must be ODD — chaos
    ordinals alternate cycle/payload round trips, and an even period
    pins every delay on the cycle-response read, where the following
    payload-exchange barrier re-synchronizes the world before the next
    arrival (the lateness then shows in the wait-vs-execute breakdown,
    not the spread)."""
    results, summary, _out = _run_tracing_world(
        tmp_path, "soak", steps=40, chaos="delay@rank2:80ms:every3",
        np_=3)
    report = [r for r in results if r["rank"] == 0][0]["report"]
    assert report["dominant_rank"] == 2, report
    assert summary["ranks"] == 3
