"""Surgical recovery plane (docs/recovery.md).

Named ``test_zz*`` past the 870 s tier-1 truncation point on purpose
(the PR 11–18 convention): the fencing / ledger / grammar units are
cheap, but the warm-recovery worlds each spawn 4-process elastic runs
and the dryrun certification spawns two.

Coverage per the ISSUE-19 battery: the worker-side warm gate and its
documented degrades (native controller, non-elastic jobs, user-code
faults), the recovery-barrier epoch fencing on the elastic service
(park, poll verdicts, begin_epoch aging), the in-process env swap of
``apply_assignment``, the blacklist ledger's ``HOROVOD_BLACKLIST_FORGIVE_S``
strike decay (evictions NEVER forgiven), the deterministic standby
successor plan (``successor_of``, ``HOROVOD_ISLAND_HEADS``
parse/format round-trip, the driver's ``_plan_successions``), the
``partition@islandN:cycleK:durS`` chaos grammar (parse/describe/replay
determinism, loud rejections, exclusion from the wire injector), the
wire-registry rows for the recover/succession RPC tags, the
metrics-summary recovery section — and, slow tier, the 4-process
kill-one-rank warm recovery on BOTH negotiation cores plus the full
``dryrun_recovery`` certification.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from horovod_tpu.chaos import (
    ChaosSpecError,
    injector_from_env,
    parse_chaos_spec,
    partition_for_island,
)
from horovod_tpu.elastic.driver import _plan_successions, _SlotLedger
from horovod_tpu.elastic.recovery import (
    apply_assignment,
    recovery_window_s,
    warm_enabled_env,
)
from horovod_tpu.ops.hierarchy import (
    format_head_overrides,
    parse_head_overrides,
    plan_topology,
)

pytestmark = pytest.mark.recovery

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- worker-side warm gate (the degrade matrix) --------------------------------


def test_warm_gate_default_on_and_opt_out():
    assert warm_enabled_env({})
    assert warm_enabled_env({"HOROVOD_RECOVERY_WARM": "1"})
    assert not warm_enabled_env({"HOROVOD_RECOVERY_WARM": "0"})
    assert not warm_enabled_env({"HOROVOD_RECOVERY_WARM": "false"})
    assert not warm_enabled_env({"HOROVOD_RECOVERY_WARM": ""})


def test_warm_gate_native_controller_degrades_to_cold():
    # the native controller's binary wire has no re-hello path: warm
    # must never engage there, whatever the opt-in says
    assert not warm_enabled_env({"HOROVOD_NATIVE_CONTROLLER": "1"})
    assert not warm_enabled_env({"HOROVOD_NATIVE_CONTROLLER": "1",
                                 "HOROVOD_RECOVERY_WARM": "1"})
    assert warm_enabled_env({"HOROVOD_NATIVE_CONTROLLER": "0"})


def test_recovery_window_parse_and_defaults():
    assert recovery_window_s({}) == 15.0
    assert recovery_window_s({"HOROVOD_RECOVERY_WINDOW_S": "3.5"}) == 3.5
    assert recovery_window_s({"HOROVOD_RECOVERY_WINDOW_S": "bogus"}) == 15.0


def test_maybe_recover_refuses_outside_elastic_or_user_faults(monkeypatch):
    from horovod_tpu.elastic.recovery import maybe_recover

    # not an elastic job: nobody to park with
    monkeypatch.delenv("HOROVOD_ELASTIC_PORT", raising=False)
    assert maybe_recover(0, {"world_fault": True}) is None
    # user-code failure: fail fast, never park (port present but the
    # record says the fn itself raised)
    monkeypatch.setenv("HOROVOD_ELASTIC_PORT", "1")
    assert maybe_recover(0, {"world_fault": False}) is None


def test_apply_assignment_swaps_managed_env_in_process(monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "3")
    monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "0")
    monkeypatch.setenv("HOROVOD_CONTROLLER_FD", "7")  # dead epoch's fd
    monkeypatch.setenv("TPU_STALE_KEY", "x")
    monkeypatch.setenv("PATH_LIKE_UNMANAGED", "keep")
    new_rank = apply_assignment({
        "HOROVOD_RANK": "1", "HOROVOD_ELASTIC_EPOCH": "1",
        "HOROVOD_CONTROLLER_ADDR": "127.0.0.1"})
    assert new_rank == 1
    assert os.environ["HOROVOD_ELASTIC_EPOCH"] == "1"
    # managed keys absent from the block are REMOVED — critically the
    # launcher-inherited listener fds of the dead epoch
    assert "HOROVOD_CONTROLLER_FD" not in os.environ
    assert "TPU_STALE_KEY" not in os.environ
    # unmanaged keys are never touched
    assert os.environ["PATH_LIKE_UNMANAGED"] == "keep"


def test_world_epoch_reads_env_live(monkeypatch):
    from horovod_tpu.basics import world_epoch

    monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "0")
    assert world_epoch() == 0
    # the warm path bumps the epoch IN-PROCESS: a cached read would
    # re-fire epoch-0-gated chaos in the recovered world
    monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "2")
    assert world_epoch() == 2


# -- the recovery barrier (driver side, epoch fencing) -------------------------


def _service():
    from horovod_tpu.elastic.health import ElasticService
    from horovod_tpu.runner.network import make_secret

    return ElasticService(bytes.fromhex(make_secret()),
                          heartbeat_interval_s=0.2, miss_limit=3)


def test_recovery_barrier_park_poll_and_verdicts():
    service = _service()
    try:
        assert service._handle(("recover", 0, 2, 4242), None) == ("ok",)
        assert service.parked(0) == {2: 4242}
        assert service.parked_pids(0) == {4242}
        assert service.parked_epochs() == [0]
        # no plan yet: poll parks
        assert service._handle(("recover_poll", 0, 2), None) == ("wait",)
        service.publish_recovery(0, {2: {"HOROVOD_RANK": "2"}})
        kind, env = service._handle(("recover_poll", 0, 2), None)
        assert kind == "assign" and env == {"HOROVOD_RANK": "2"}
        # a parked rank NOT in the plan is told to exit
        service._handle(("recover", 0, 3, 4243), None)
        kind, reason = service._handle(("recover_poll", 0, 3), None)
        assert kind == "exit" and "not reused" in reason
        # the empty plan is the explicit everyone-out verdict
        service.publish_recovery(0, {})
        assert service._handle(("recover_poll", 0, 2), None)[0] == "exit"
    finally:
        service.shutdown()


def test_recovery_barrier_epoch_fencing_and_aging():
    service = _service()
    try:
        service._handle(("recover", 0, 1, 100), None)
        # epoch 0's survivors park WHILE begin_epoch(1) runs: the barrier
        # must survive exactly one successor epoch...
        service.begin_epoch(1)
        assert service.parked(0) == {1: 100}
        # ...and age out after two (a finished or abandoned round)
        service.begin_epoch(2)
        assert service.parked(0) == {}
        assert service.parked_epochs() == []
        # distinct epochs are distinct barriers
        service._handle(("recover", 2, 0, 200), None)
        service._handle(("recover", 3, 0, 300), None)
        assert service.parked(2) == {0: 200}
        assert service.parked(3) == {0: 300}
    finally:
        service.shutdown()


def test_wait_parked_returns_early_on_full_set():
    import time

    service = _service()
    try:
        service._handle(("recover", 0, 0, 10), None)
        service._handle(("recover", 0, 1, 11), None)
        t0 = time.monotonic()
        got = service.wait_parked(0, {0, 1}, deadline_s=5.0)
        assert got == {0: 10, 1: 11}
        assert time.monotonic() - t0 < 1.0  # early exit, not the deadline
    finally:
        service.shutdown()


# -- blacklist ledger: strike decay, evictions permanent -----------------------


def test_slot_ledger_permanent_without_forgiveness():
    ledger = _SlotLedger(np=3, limit=2, forgive_s=0.0)
    ledger.strike(1, now=0.0)
    ledger.strike(1, now=1.0)
    assert ledger.active(now=2.0) == [0, 2]
    # no decay, ever: the original PR 2 semantics
    assert ledger.active(now=1e9) == [0, 2]
    assert ledger.blacklisted(now=1e9) == [1]


def test_slot_ledger_forgiveness_ages_strikes_out():
    ledger = _SlotLedger(np=2, limit=2, forgive_s=10.0)
    ledger.strike(0, now=0.0)
    ledger.strike(0, now=1.0)
    assert ledger.active(now=2.0) == [1]
    # 10s after the FIRST strike it decays: one live strike < limit
    assert ledger.active(now=10.5) == [0, 1]
    assert ledger.blacklisted(now=12.0) == []


def test_slot_ledger_evictions_are_never_forgiven():
    ledger = _SlotLedger(np=2, limit=2, forgive_s=1.0)
    ledger.evict(1)  # an enforced StragglerEvictError verdict
    assert ledger.active(now=0.0) == [0]
    assert ledger.active(now=1e9) == [0]
    assert ledger.blacklisted(now=1e9) == [1]


def test_blacklist_forgive_env_parse(monkeypatch):
    from horovod_tpu.elastic.driver import _blacklist_forgive_s

    monkeypatch.delenv("HOROVOD_BLACKLIST_FORGIVE_S", raising=False)
    assert _blacklist_forgive_s() == 0.0
    monkeypatch.setenv("HOROVOD_BLACKLIST_FORGIVE_S", "30")
    assert _blacklist_forgive_s() == 30.0
    monkeypatch.setenv("HOROVOD_BLACKLIST_FORGIVE_S", "junk")
    assert _blacklist_forgive_s() == 0.0


# -- standby succession plan (deterministic at plan time) ----------------------


def test_successor_is_lowest_non_head_member():
    topo = plan_topology(8, "islands:2")
    for island, members in topo.islands.items():
        head = topo.head_of(island)
        assert topo.successor_of(island) == min(
            r for r in members if r != head)
    # a single-member island has nobody to succeed
    solo = plan_topology(4, "islands:4")
    assert all(solo.successor_of(i) is None for i in solo.islands)


def test_successor_tracks_head_overrides():
    # after succession the OLD successor is the head; the next standby
    # must re-derive deterministically from the surviving membership
    topo = plan_topology(8, "islands:2", head_overrides={1: 5})
    assert topo.head_of(1) == 5
    assert topo.successor_of(1) == min(
        r for r in topo.islands[1] if r != 5)
    # an override naming a rank outside the island is ignored
    bogus = plan_topology(8, "islands:2", head_overrides={1: 0})
    assert bogus.head_of(1) == min(bogus.islands[1])


def test_head_overrides_parse_format_round_trip():
    overrides = {0: 1, 1: 3}
    raw = format_head_overrides(overrides)
    assert raw == "0:1,1:3"
    assert parse_head_overrides(raw) == overrides
    assert parse_head_overrides("") == {}
    assert parse_head_overrides(None) == {}
    # torn values degrade to the planned heads, never crash launch
    assert parse_head_overrides("1:3,junk,:,8") == {1: 3}


def test_plan_successions_promotes_standby_for_dead_head():
    env = {"HOROVOD_HIERARCHY": "islands:2"}
    # rank 2 heads island 1 of the 4-rank world; its death promotes 3
    out = _plan_successions({}, failed={2}, world=4, env=env)
    assert out == {1: 3}
    # a dead MEMBER plans nothing
    assert _plan_successions({}, failed={3}, world=4, env=env) == {}
    # flat worlds have no heads to succeed
    assert _plan_successions({}, failed={2}, world=4,
                             env={"HOROVOD_HIERARCHY": "flat"}) == {}
    # an already-promoted head dying promotes the NEXT survivor
    out = _plan_successions({1: 3}, failed={3}, world=4, env=env)
    assert out == {1: 2}


# -- partition chaos grammar ---------------------------------------------------


def test_partition_clause_parses_and_replays_deterministically():
    spec = "partition@island1:cycle3:dur0.4s"
    plan = parse_chaos_spec(spec)
    (rule,) = plan.rules
    assert rule.kind == "partition"
    assert rule.rank == 1          # island, in the partition grammar
    assert rule.ordinal == 3       # cycle
    assert rule.delay_s == pytest.approx(0.4)
    # replay determinism: the same spec parses to the same plan, and
    # describe() round-trips the clause for the injection note
    again = parse_chaos_spec(spec)
    assert again.rules[0].describe() == rule.describe()
    ms = parse_chaos_spec("partition@island0:cycle1:dur250ms").rules[0]
    assert ms.delay_s == pytest.approx(0.25)


@pytest.mark.parametrize("bad", [
    "partition@island1:cycle3",          # no duration
    "partition@island1:cycle3:0.4s",     # missing dur prefix
    "partition@islandX:cycle3:dur1s",    # island not an int
    "partition@island1:cycleX:dur1s",    # cycle not an int
    "partition@rank1:cycle3:dur1s",      # partitions target islands
])
def test_partition_malformed_clauses_fail_loudly(bad):
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec(bad)


def test_partition_excluded_from_wire_injector(monkeypatch):
    # island-level faults fire in the sub-coordinator, not per-message:
    # the wire injector must NOT arm them
    monkeypatch.setenv("HOROVOD_CHAOS",
                       "partition@island1:cycle2:dur1s")
    injector = injector_from_env(rank=1)
    assert injector is None or not injector._rules


def test_partition_for_island_reads_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHAOS",
                       "partition@island1:cycle3:dur0.4s")
    assert partition_for_island(1) == (3, pytest.approx(0.4))
    assert partition_for_island(0) is None
    monkeypatch.setenv("HOROVOD_CHAOS", "")
    assert partition_for_island(1) is None


# -- registry / docs / tooling rows --------------------------------------------


def test_wire_registry_names_recovery_rpc_tags():
    from horovod_tpu.analysis.wire_registry import ELASTIC_RPC_TAGS

    for tag in ("recover", "recover_poll"):
        assert tag in ELASTIC_RPC_TAGS and ELASTIC_RPC_TAGS[tag].strip()
        assert "recovery" in ELASTIC_RPC_TAGS[tag].lower()


def test_recovery_grid_shape():
    from horovod_tpu.chaos.matrix import RECOVERY_GRID

    cells = dict(RECOVERY_GRID)
    assert set(cells) == {"kill-rank-warm", "partition-heal",
                          "partition-escalate", "head-kill",
                          "succession-live"}
    # every cell lands in exactly one certified bucket — never a hang
    assert set(cells.values()) <= {"healed", "recovered"}


def test_metrics_summary_renders_recovery_section(tmp_path):
    from horovod_tpu.elastic import driver as _driver
    from horovod_tpu.obs.registry import registry
    from horovod_tpu.ops import hierarchy as hier

    _driver._RECOVERY_WARM.inc()
    _driver._RECOVERY_SURVIVORS.inc(3)
    _driver._RECOVERY_MTTR.labels(mode="warm").observe(2.5)
    hier.SUCCESSIONS.inc()
    snap = registry().snapshot()
    assert "horovod_recovery_warm_relaunches_total" in snap, sorted(snap)
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(snap))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "metrics_summary.py"), str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "recovery plane" in proc.stdout
    assert "horovod_recovery_warm_relaunches_total" in proc.stdout
    assert "horovod_recovery_successions_total" in proc.stdout


def test_recovery_docs_exist_with_the_ladder_and_knobs():
    docs = os.path.join(REPO, "docs", "recovery.md")
    with open(docs, encoding="utf-8") as fh:
        text = fh.read()
    for needle in ("HOROVOD_RECOVERY_WARM", "HOROVOD_RECOVERY_WINDOW_S",
                   "HOROVOD_BLACKLIST_FORGIVE_S", "HOROVOD_ISLAND_HEADS",
                   "partition@island", "headstop@",
                   "reconnect", "succession", "cold"):
        assert needle in text, needle


# -- multi-process warm recovery (slow tier) -----------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("native_core", [0, 1])
def test_kill_one_rank_warm_recovers_bit_exact(native_core):
    from horovod_tpu.chaos.matrix import run_recovery_cell

    cell = run_recovery_cell("kill-rank-warm", native_core=native_core)
    assert cell["outcome"] == "recovered", cell
    assert cell["verdict"] == "recovered@epoch1 survivors=3/4", cell
    by_rank = {r["rank"]: r for r in cell["results"]}
    # bit-exact to the full-job answer, restored from a SEALED commit
    assert all(r["w0"] == by_rank[0]["w0"] for r in cell["results"])
    assert any("sealed" in str(r["restore"]) for r in cell["results"])


@pytest.mark.slow
def test_dryrun_recovery_certification():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from __graft_entry__ import dryrun_recovery

    dryrun_recovery()
