"""Example smoke tests — every example must actually run (the reference's
examples are its de-facto integration suite; SURVEY §2.8).

Examples are executed in subprocesses with the platform pinned to CPU
*after* jax import (the TPU plugin prepends itself to JAX_PLATFORMS, so an
env var alone cannot keep subprocesses off the bench chip)."""

import os
import subprocess
import sys

import pytest


# Subprocess/soak-heavy by design: excluded from the quick tier (-m "not soak").
pytestmark = pytest.mark.soak

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script: str, argv, timeout: float = 300.0, env=None):
    bootstrap = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import runpy, sys; "
        f"sys.argv = [{script!r}] + {list(argv)!r}; "
        f"runpy.run_path({os.path.join(_ROOT, 'examples', script)!r}, "
        "run_name='__main__')"
    )
    full_env = dict(os.environ)
    full_env.pop("JAX_PLATFORMS", None)
    full_env.setdefault(
        "XLA_FLAGS",
        "--xla_force_host_platform_device_count=2")
    if env:
        full_env.update(env)
    result = subprocess.run(
        [sys.executable, "-c", bootstrap], cwd=_ROOT, env=full_env,
        capture_output=True, text=True, timeout=timeout)
    assert result.returncode == 0, (
        f"{script} failed:\nstdout:\n{result.stdout}\n"
        f"stderr:\n{result.stderr}")
    return result


def test_jax_mnist_eager():
    out = _run_example("jax_mnist_eager.py",
                       ["--steps", "12", "--batch-size", "16"])
    assert "step 0: loss=" in out.stdout
    assert "done" in out.stdout


@pytest.mark.parametrize("mode", ["dp", "ring", "ulysses"])
def test_jax_transformer_lm(mode):
    out = _run_example(
        "jax_transformer_lm.py",
        ["--mode", mode, "--steps", "12", "--seq-len", "64",
         "--batch-size", "8"],
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    lines = [l for l in out.stdout.splitlines() if l.startswith("step")]
    losses = [float(l.split("loss=")[1].split()[0]) for l in lines]
    assert losses[-1] < losses[0], (mode, losses)
    assert "done" in out.stdout


def test_flax_mnist_frontend():
    out = _run_example("flax_mnist.py",
                       ["--epochs", "1", "--batch-size", "8"])
    assert "epoch 0: loss" in out.stdout
    assert "restored at step" in out.stdout


def test_flax_mnist_advanced_callbacks():
    out = _run_example(
        "flax_mnist_advanced.py",
        ["--epochs", "3", "--batch-size", "8", "--warmup-epochs", "2"])
    lines = [l for l in out.stdout.splitlines() if l.startswith("epoch")]
    assert len(lines) == 3
    # warmup must raise the LR from base toward base * num_devices
    lrs = [float(l.split("lr=")[1].split()[0]) for l in lines]
    assert lrs[-1] > lrs[0]


def test_pytorch_synthetic_benchmark():
    out = _run_example(
        "pytorch_synthetic_benchmark.py",
        ["--batch-size", "4", "--image-size", "32", "--num-iters", "2",
         "--num-warmup-batches", "1", "--num-batches-per-iter", "1"])
    assert "Img/sec per rank" in out.stdout


def test_pytorch_synthetic_benchmark_device_plane_json():
    """The watcher's torch_synthetic entry: explicit size-1 XLA data plane
    (grad bytes ride H2D -> compiled reduce -> D2H) and a self-describing
    JSON capture line in the bench.py protocol."""
    import json

    out = _run_example(
        "pytorch_synthetic_benchmark.py",
        ["--batch-size", "4", "--image-size", "32", "--num-iters", "2",
         "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
         "--json"],
        env={"HOROVOD_DATA_PLANE": "xla"})
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "torch_synthetic_train_images_per_sec_per_rank"
    assert rec["data_plane"] == "xla"
    assert rec["front_end"] == "torch"
    assert rec["live"] is True
    assert rec["value"] > 0
    assert rec["n_ranks"] == 1
    assert rec["git_sha"]


def test_run_fn_job():
    out = _run_example("run_fn_job.py", [],
                       env={"EXAMPLE_PLATFORM": "cpu"})
    assert "OK" in out.stdout


def test_jax_tabular_job():
    """The end-to-end data job (keras_spark_rossmann analog): driver
    feature engineering -> run_fn training world with sharded rows,
    warmup, metric averaging, rank-0 checkpoint -> driver restore +
    submission CSV."""
    out = _run_example("jax_tabular_job.py",
                       ["--rows", "768", "--epochs", "2",
                        "--batch-size", "96"],
                       env={"EXAMPLE_PLATFORM": "cpu"}, timeout=420.0)
    assert "submission written" in out.stdout
    assert "OK" in out.stdout


def test_jax_mnist():
    out = _run_example("jax_mnist.py",
                       ["--epochs", "1", "--batch-size", "8"])
    assert out.returncode == 0


def test_tensorflow_mnist():
    out = _run_example(
        "tensorflow_mnist.py",
        ["--epochs", "1", "--batch-size", "32", "--samples", "64"])
    assert "epoch 0: loss=" in out.stdout
    assert "done" in out.stdout


def test_tensorflow_mnist_eager():
    out = _run_example(
        "tensorflow_mnist_eager.py",
        ["--batches", "12", "--batch-size", "16"])
    assert "Step #0\tLoss:" in out.stdout
    assert "done" in out.stdout


def test_pytorch_imagenet_resnet50(tmp_path):
    """The production-loop example: gradient accumulation, fp16 wire
    compression, checkpoint save — then a second run that must resume from
    the broadcast epoch instead of retraining."""
    fmt = str(tmp_path / "ckpt-{epoch}.pth.tar")
    argv = ["--epochs", "1", "--image-size", "64", "--train-batches", "2",
            "--batch-size", "8", "--batches-per-allreduce", "2",
            "--num-classes", "10", "--fp16-allreduce",
            "--checkpoint-format", fmt]
    out = _run_example("pytorch_imagenet_resnet50.py", argv, timeout=600.0)
    assert "epoch 0: loss=" in out.stdout
    assert os.path.exists(fmt.format(epoch=1))
    # resume: epoch 1 checkpoint exists -> nothing left to train
    out2 = _run_example("pytorch_imagenet_resnet50.py", argv, timeout=600.0)
    assert "epoch 0" not in out2.stdout
    assert "done" in out2.stdout


def test_pytorch_mnist():
    out = _run_example("pytorch_mnist.py",
                       ["--epochs", "1", "--batch-size", "8"])
    assert "epoch 0: loss=" in out.stdout


def test_jax_imagenet_resnet50():
    out = _run_example(
        "jax_imagenet_resnet50.py",
        ["--epochs", "1", "--steps-per-epoch", "2", "--batch-size", "4",
         "--image-size", "64", "--warmup-epochs", "1"],
        timeout=600.0)
    assert "epoch 0: loss=" in out.stdout


def test_jax_word2vec():
    out = _run_example(
        "jax_word2vec.py",
        ["--vocab-size", "200", "--embedding-dim", "16",
         "--batch-size", "32", "--steps", "12"])
    assert "loss=" in out.stdout


def test_haiku_mnist():
    out = _run_example("haiku_mnist.py",
                       ["--steps", "10", "--batch-size", "8"])
    assert out.returncode == 0


def test_scaling_bench_smoke():
    """The scaling-curve harness (BASELINE.md north star) must produce a
    point per device count and the efficiency table."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks",
                                      "scaling_bench.py"),
         "--devices", "1,2", "--batch-size", "4", "--iters", "1",
         "--batches-per-iter", "1"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert '"devices": 2' in result.stdout
    assert "efficiency" in result.stdout


def test_fusion_bench_smoke():
    """The fusion micro-benchmark (docs/benchmarks.md) must run end to end
    on tiny sizes; its workers spawn their own 2-process worlds."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks",
                                      "fusion_bench.py"),
         "--tensors", "4", "--elems", "256", "--rounds", "2"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "xla" in result.stdout and "host" in result.stdout
