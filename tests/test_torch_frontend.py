"""PyTorch front-end (reference: ``test/test_torch.py`` optimizer and op
tests, run against the TPU-native engine)."""

import numpy as np
import pytest
import torch

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch


def test_torch_allreduce_roundtrip(hvd):
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd_torch.allreduce(t, average=False, name="t.ar")
    assert isinstance(out, torch.Tensor)
    np.testing.assert_array_equal(out.numpy(), t.numpy())


def test_torch_bf16_roundtrip(hvd):
    t = torch.ones(4, dtype=torch.bfloat16)
    out = hvd_torch.allreduce(t, average=True, name="t.bf16")
    assert out.dtype == torch.bfloat16
    np.testing.assert_array_equal(out.float().numpy(), 1.0)


def test_torch_broadcast_and_allgather(hvd):
    t = torch.full((3,), 5.0)
    np.testing.assert_array_equal(
        hvd_torch.broadcast(t, 0, name="t.b").numpy(), 5.0)
    np.testing.assert_array_equal(
        hvd_torch.allgather(t, name="t.g").numpy(), t.numpy())


def test_distributed_optimizer_size1_matches_sgd(hvd):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    ref = torch.nn.Linear(4, 2)
    ref.load_state_dict(model.state_dict())

    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)

    x = torch.randn(8, 4)
    model(x).sum().backward()
    ref(x).sum().backward()
    opt.step()
    ref_opt.step()
    for p, q in zip(model.parameters(), ref.parameters()):
        np.testing.assert_allclose(p.detach().numpy(), q.detach().numpy(),
                                   rtol=1e-6)


def test_distributed_optimizer_duplicate_names_rejected(hvd):
    model = torch.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="unique"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("same", p) for p in model.parameters()])


def test_broadcast_parameters_state_dict(hvd):
    model = torch.nn.Linear(2, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(v.numpy(), before[k].numpy())


def test_torch_multiprocess_world():
    from test_multiprocess import _run_world

    _run_world("torch", 2, timeout=120.0)


def test_torch_divergent_optimizer_state_multiprocess():
    """Root restored from checkpoint, workers fresh: structure must sync
    without deadlock (coordinator-matched collectives)."""
    from test_multiprocess import _run_world

    _run_world("torch_state", 2, timeout=120.0)
