"""PyTorch front-end (reference: ``test/test_torch.py`` optimizer and op
tests, run against the TPU-native engine)."""

import numpy as np
import pytest
import torch

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch


def test_torch_allreduce_roundtrip(hvd):
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd_torch.allreduce(t, average=False, name="t.ar")
    assert isinstance(out, torch.Tensor)
    np.testing.assert_array_equal(out.numpy(), t.numpy())


def test_torch_bf16_roundtrip(hvd):
    t = torch.ones(4, dtype=torch.bfloat16)
    out = hvd_torch.allreduce(t, average=True, name="t.bf16")
    assert out.dtype == torch.bfloat16
    np.testing.assert_array_equal(out.float().numpy(), 1.0)


def test_torch_broadcast_and_allgather(hvd):
    t = torch.full((3,), 5.0)
    np.testing.assert_array_equal(
        hvd_torch.broadcast(t, 0, name="t.b").numpy(), 5.0)
    np.testing.assert_array_equal(
        hvd_torch.allgather(t, name="t.g").numpy(), t.numpy())


def test_torch_inplace_and_async_variants(hvd):
    """In-place variants write the result back into the caller's tensor and
    return it (reference ``mpi_ops.py:156-178, 361-404``); async variants
    return handles usable with poll/synchronize."""
    t = torch.full((4,), 3.0)
    out = hvd_torch.allreduce_(t, average=False, name="t.ar_")
    assert out is t
    np.testing.assert_array_equal(t.numpy(), 3.0)  # world of 1: identity

    # leaf parameters with requires_grad are the canonical in-place target
    # (syncing model weights); the write must not trip autograd
    p = torch.nn.Parameter(torch.full((3,), 2.0))
    assert hvd_torch.broadcast_(p, 0, name="t.p_") is p
    assert hvd_torch.allreduce_(p, average=True, name="t.par_") is p

    t2 = torch.full((2, 2), 7.0)
    h = hvd_torch.allreduce_async_(t2, average=True, name="t.ara_")
    out2 = hvd_torch.synchronize(h)
    assert out2 is t2

    b = torch.full((3,), 9.0)
    out3 = hvd_torch.broadcast_(b, 0, name="t.b_")
    assert out3 is b

    h2 = hvd_torch.broadcast_async_(b, 0, name="t.ba_")
    assert hvd_torch.synchronize(h2) is b

    h3 = hvd_torch.allgather_async(torch.ones(2), name="t.ga")
    np.testing.assert_array_equal(
        hvd_torch.synchronize(h3).numpy(), 1.0)
    h4 = hvd_torch.broadcast_async(torch.ones(2), 0, name="t.ba")
    np.testing.assert_array_equal(
        hvd_torch.synchronize(h4).numpy(), 1.0)


def test_torch_autograd_allreduce(hvd):
    """Collectives are differentiable torch ops (reference
    ``test_torch.py:377-428``, ``mpi_ops.py:110-121``): at size 1 the
    allreduce is identity, so d(sum(allreduce(x) * w))/dx == w."""
    x = torch.arange(4, dtype=torch.float32, requires_grad=True)
    w = torch.tensor([1.0, 2.0, 3.0, 4.0])
    y = hvd_torch.allreduce(x, average=False, name="ag.ar")
    (y * w).sum().backward()
    np.testing.assert_array_equal(x.grad.numpy(), w.numpy())


def test_torch_autograd_allgather_and_broadcast(hvd):
    x = torch.ones(3, 2, requires_grad=True)
    y = hvd_torch.allgather(x, name="ag.g")
    y.sum().backward()
    # size-1: the gathered output IS the input; grad of sum is ones
    np.testing.assert_array_equal(x.grad.numpy(), np.ones((3, 2)))

    z = torch.ones(4, requires_grad=True)
    out = hvd_torch.broadcast(z, root_rank=0, name="ag.b")
    (out * 2).sum().backward()
    # rank 0 IS the root at size 1: all gradient flows back
    np.testing.assert_array_equal(z.grad.numpy(), np.full(4, 2.0))


def test_distributed_optimizer_size1_matches_sgd(hvd):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    ref = torch.nn.Linear(4, 2)
    ref.load_state_dict(model.state_dict())

    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)

    x = torch.randn(8, 4)
    model(x).sum().backward()
    ref(x).sum().backward()
    opt.step()
    ref_opt.step()
    for p, q in zip(model.parameters(), ref.parameters()):
        np.testing.assert_allclose(p.detach().numpy(), q.detach().numpy(),
                                   rtol=1e-6)


def test_distributed_optimizer_duplicate_names_rejected(hvd):
    model = torch.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="unique"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("same", p) for p in model.parameters()])


def test_broadcast_parameters_state_dict(hvd):
    model = torch.nn.Linear(2, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(v.numpy(), before[k].numpy())


def test_torch_multiprocess_world():
    from test_multiprocess import _run_world

    _run_world("torch", 2, timeout=120.0)


def test_torch_divergent_optimizer_state_multiprocess():
    """Root restored from checkpoint, workers fresh: structure must sync
    without deadlock (coordinator-matched collectives)."""
    from test_multiprocess import _run_world

    _run_world("torch_state", 2, timeout=120.0)
