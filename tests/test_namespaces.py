"""Every framework namespace must carry the full process-control surface.

The reference re-exports ``init/shutdown/rank/size/...`` inside each
front-end module so users write ``import horovod.torch as hvd`` and never
touch another namespace (``torch/mpi_ops.py:42-51``,
``keras/__init__.py``); drop-in parity requires the same here.
"""

import importlib

import pytest

PROCESS_SURFACE = [
    "init", "shutdown", "is_initialized", "rank", "size",
    "local_rank", "local_size", "cross_rank", "cross_size",
    "mpi_threads_supported",
]


@pytest.mark.parametrize("module", [
    "horovod_tpu",
    "horovod_tpu.torch",
    "horovod_tpu.tensorflow",
    "horovod_tpu.tensorflow.keras",
    "horovod_tpu.keras",
    "horovod_tpu.flax",
    "horovod_tpu.haiku",
])
def test_process_surface(module):
    mod = importlib.import_module(module)
    missing = [s for s in PROCESS_SURFACE if not hasattr(mod, s)]
    assert not missing, f"{module} lacks {missing}"


def test_torch_op_surface():
    """The reference's full op set incl. in-place and async variants
    (``torch/mpi_ops.py:86-438``)."""
    mod = importlib.import_module("horovod_tpu.torch")
    ops = ["allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
           "allgather", "allgather_async",
           "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
           "poll", "synchronize", "Compression"]
    missing = [s for s in ops if not hasattr(mod, s)]
    assert not missing, f"horovod_tpu.torch lacks {missing}"
