"""Test fixture: a virtual 8-device CPU world.

The reference's fixture is single-process MPI (a self-initialized world of
size 1) that becomes a true multi-process test under ``mpirun -np N``
(SURVEY §4). Ours: a single process with 8 virtual XLA CPU devices for SPMD
collectives, plus subprocess-based launcher tests for true multi-process
negotiation (``test_multiprocess.py``). Env must be set before jax imports.
"""

import os

# Force CPU for tests even when the session env points at a real TPU: tests
# must run on the virtual 8-device mesh and never touch the bench chip. The
# TPU plugin prepends itself to JAX_PLATFORMS, so the env var alone is not
# enough — override the config after import, before any backend spins up.
os.environ.pop("JAX_PLATFORMS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd_mod

    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()
