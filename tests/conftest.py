"""Test fixture: a virtual 8-device CPU world.

The reference's fixture is single-process MPI (a self-initialized world of
size 1) that becomes a true multi-process test under ``mpirun -np N``
(SURVEY §4). Ours: a single process with 8 virtual XLA CPU devices for SPMD
collectives, plus subprocess-based launcher tests for true multi-process
negotiation (``test_multiprocess.py``). Env must be set before jax imports.
"""

# Force CPU for tests even when the session env points at a real TPU: tests
# must run on the virtual 8-device mesh and never touch the bench chip.
# Importing the helper executes horovod_tpu/__init__.py first; that chain
# performs no backend query today, and pin_cpu_platform verifies the pinned
# platform and raises if any future import defeats the pin.
from horovod_tpu.core.platform import pin_cpu_platform

pin_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="session")
def _flightrec_dir_tmp(tmp_path_factory):
    """The flight recorder is ALWAYS-ON (docs/blackbox.md), and abort
    tests — chaos cells, stall escalations, elastic kills — would
    otherwise litter the repo cwd with blackbox-*.json incident files.
    Point the dump dir at a session tmp dir (inherited by spawned
    worker worlds via the environment); tests that assert on incident
    files set their own dir explicitly."""
    import os

    from horovod_tpu.core.config import HOROVOD_FLIGHTREC_DIR

    from horovod_tpu.core.config import HOROVOD_FLIGHTREC_LAUNCH_GRACE

    # Pin the launcher's evidence grace to 0 for the whole suite: dozens
    # of tests exercise hard rank deaths and rely on fail-fast teardown
    # timing; the handful that assert on the grace-landed dump set the
    # knob themselves.
    if not os.environ.get(HOROVOD_FLIGHTREC_LAUNCH_GRACE):
        os.environ[HOROVOD_FLIGHTREC_LAUNCH_GRACE] = "0"
    if os.environ.get(HOROVOD_FLIGHTREC_DIR):
        yield
        return
    os.environ[HOROVOD_FLIGHTREC_DIR] = str(
        tmp_path_factory.mktemp("blackbox"))
    yield
    os.environ.pop(HOROVOD_FLIGHTREC_DIR, None)


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd_mod

    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()
