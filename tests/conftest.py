"""Test fixture: a virtual 8-device CPU world.

The reference's fixture is single-process MPI (a self-initialized world of
size 1) that becomes a true multi-process test under ``mpirun -np N``
(SURVEY §4). Ours: a single process with 8 virtual XLA CPU devices for SPMD
collectives, plus subprocess-based launcher tests for true multi-process
negotiation (``test_multiprocess.py``). Env must be set before jax imports.
"""

# Force CPU for tests even when the session env points at a real TPU: tests
# must run on the virtual 8-device mesh and never touch the bench chip.
# Importing the helper executes horovod_tpu/__init__.py first; that chain
# performs no backend query today, and pin_cpu_platform verifies the pinned
# platform and raises if any future import defeats the pin.
from horovod_tpu.core.platform import pin_cpu_platform

pin_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd_mod

    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()
