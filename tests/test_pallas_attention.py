"""Pallas flash-attention kernel vs dense reference (interpret mode on the
CPU suite; the same kernel compiles for real on TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.pallas_attention import flash_attention
from horovod_tpu.parallel.ring_attention import dense_attention

B, T, H, D = 2, 64, 2, 16


def _qkv(seed):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_q_offset_matches_shifted_causal():
    """q_offset reproduces ring attention's per-shard causal masking: a
    q block at global offset sees all earlier K."""
    q, k, v = _qkv(1)
    offset = 16
    out = flash_attention(q[:, :16], k[:, :32], v[:, :32], causal=True,
                          block_q=16, block_k=16, q_offset=offset)
    # dense equivalent: q rows at positions 16..31 attending over k 0..31
    s_ref = dense_attention(
        jnp.pad(q[:, :16], ((0, 0), (16, 0), (0, 0), (0, 0))),
        k[:, :32], v[:, :32], causal=True)[:, 16:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(s_ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_rejects_ragged_seq():
    q = jnp.ones((1, 48, 1, 8))
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, q, q, block_q=32, block_k=32)
