"""Pallas flash-attention kernel vs dense reference (interpret mode on the
CPU suite; the same kernel compiles for real on TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.pallas_attention import flash_attention
from horovod_tpu.parallel.ring_attention import dense_attention

B, T, H, D = 2, 64, 2, 16


def _qkv(seed):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_q_offset_matches_shifted_causal():
    """q_offset reproduces ring attention's per-shard causal masking: a
    q block at global offset sees all earlier K."""
    q, k, v = _qkv(1)
    offset = 16
    out = flash_attention(q[:, :16], k[:, :32], v[:, :32], causal=True,
                          block_q=16, block_k=16, q_offset=offset)
    # dense equivalent: q rows at positions 16..31 attending over k 0..31
    s_ref = dense_attention(
        jnp.pad(q[:, :16], ((0, 0), (16, 0), (0, 0), (0, 0))),
        k[:, :32], v[:, :32], causal=True)[:, 16:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(s_ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_rejects_ragged_seq():
    q = jnp.ones((1, 48, 1, 8))
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, q, q, block_q=32, block_k=32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_dense(causal):
    """custom-VJP backward kernels (FlashAttention-2 recomputation) must
    reproduce the dense-attention gradients for q, k, and v."""
    import jax

    q, k, v = _qkv(3)
    rng = np.random.default_rng(7)
    cot = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))

    def flash_loss(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal=causal,
                                        block_q=16, block_k=16), cot)

    def dense_loss(q, k, v):
        return jnp.vdot(dense_attention(q, k, v, causal=causal), cot)

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_grad_q_offset():
    """Backward with a q_offset (the ring-attention entry point): compare
    against dense attention over the equivalent shifted causal mask."""
    import jax

    from horovod_tpu.parallel.ring_attention import dense_attention as _da

    q, k, v = _qkv(5)
    half = T // 2
    q_half = q[:, half:]  # queries living at global positions [half, T)
    cot = jnp.ones_like(q_half)

    def flash_loss(q_half, k, v):
        return jnp.vdot(flash_attention(q_half, k, v, causal=True,
                                        block_q=16, block_k=16,
                                        q_offset=half), cot)

    def dense_loss(q_full, k, v):
        return jnp.vdot(_da(q_full, k, v, causal=True)[:, half:],
                        jnp.ones_like(q_full[:, half:]))

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q_half, k, v)
    ref_full = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(got[0]),
                               np.asarray(ref_full[0][:, half:]),
                               rtol=5e-4, atol=5e-4, err_msg="dq")
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref_full[1]),
                               rtol=5e-4, atol=5e-4, err_msg="dk")
    np.testing.assert_allclose(np.asarray(got[2]), np.asarray(ref_full[2]),
                               rtol=5e-4, atol=5e-4, err_msg="dv")


def test_flash_trains_in_transformer():
    """End-to-end: a TransformerLM with attention='flash' must train (the
    forward-only kernel regression this guards against)."""
    import jax
    import optax

    from horovod_tpu.models import TransformerLM, lm_loss

    model = TransformerLM(vocab_size=32, num_layers=1, num_heads=2,
                          d_model=32, d_ff=64, max_seq_len=64,
                          dtype=jnp.float32, attention="flash")
    tokens = jnp.asarray(
        np.tile(np.arange(8), (2, 8)).astype(np.int32))
    variables = model.clone(attention="dense").init(
        jax.random.PRNGKey(0), tokens[:, :8])
    opt = optax.adam(1e-2)
    opt_state = opt.init(variables)

    @jax.jit
    def step(variables, opt_state):
        loss, grads = jax.value_and_grad(
            lambda v: lm_loss(model.apply(v, tokens), tokens))(variables)
        updates, opt_state = opt.update(grads, opt_state, variables)
        return optax.apply_updates(variables, updates), opt_state, loss

    losses = []
    for _ in range(10):
        variables, opt_state, loss = step(variables, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_flash_under_vma_shard_map_matches_dense():
    """The flash kernel must be legal inside a vma-tracking shard_map (the
    DP product path wraps whole models in one): pallas_call outputs carry
    the union of their operands' vma type (_sds). Data-parallel over the
    batch, gradients and outputs must match the dense reference."""
    import jax
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh()
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(size=(8, 128, 2, 64)).astype(
        np.float32)) for _ in range(3))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    def sharded(fn):
        def inner(q, k, v):
            val, grads = jax.value_and_grad(fn, argnums=(0, 1, 2))(q, k, v)
            return jax.lax.psum(val, "data"), grads

        return jax.jit(shard_map(
            inner, mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
            out_specs=(P(), (P("data"), P("data"), P("data")))))

    val_f, grads_f = sharded(loss_flash)(q, k, v)
    val_d, grads_d = sharded(loss_dense)(q, k, v)
    np.testing.assert_allclose(float(val_f), float(val_d), rtol=2e-4)
    for gf, gd in zip(grads_f, grads_d):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-3, atol=2e-3)
