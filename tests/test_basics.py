"""Basics: init/rank/size lifecycle (reference: ``test/test_torch.py:59-71``
rank/size ground truth; ``horovod/common/__init__.py`` error semantics)."""

import pytest

import horovod_tpu as hvd


def test_uninitialized_raises():
    hvd.shutdown()
    with pytest.raises(ValueError):
        hvd.rank()
    with pytest.raises(ValueError):
        hvd.size()


def test_init_rank_size(hvd):
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.local_device_count() == 8  # virtual CPU mesh from conftest
    assert hvd.num_devices() == 8


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()
    assert hvd.rank() == 0


def test_shutdown_and_reinit(hvd):
    hvd.shutdown()
    assert not hvd.is_initialized()
    with pytest.raises(ValueError):
        hvd.rank()
    hvd.init()
    assert hvd.rank() == 0


def test_mpi_threads_supported(hvd):
    # No MPI in this build, by design (SURVEY §2.10).
    assert hvd.mpi_threads_supported() is False


def test_init_subset_validation():
    """Subset worlds (reference ``common/__init__.py:58-84``): rank lists
    are validated against the launcher world; an mpi4py communicator object
    is rejected (no MPI here); a rank list may also be spelled ``comm=``
    as the reference allows. Multi-member subsets are exercised in
    tests/test_multiprocess.py::test_mp_subset_world."""
    hvd.shutdown()
    with pytest.raises(ValueError):
        hvd.init(ranks=[0, 1])  # world of 1: rank 1 does not exist
    with pytest.raises(ValueError):
        hvd.init(ranks=[0, 0])  # duplicates
    with pytest.raises(ValueError):
        hvd.init(ranks=[])  # empty communicator is a typo, not full world
    with pytest.raises(ValueError):
        hvd.init(comm=object())  # an actual MPI communicator: unsupported

    # the self-subset of a single-process world is legal, via either
    # spelling
    hvd.init(ranks=[0])
    assert hvd.rank() == 0 and hvd.size() == 1
    hvd.shutdown()
    hvd.init(comm=[0])
    assert hvd.rank() == 0 and hvd.size() == 1
    hvd.shutdown()
