"""Transformer LM model family: attention-backend equivalence and training.

The reference has no model code (SURVEY §5.7); these tests cover the
long-context extension's flagship — the same module must produce identical
logits under dense, flash-kernel, ring (sequence-parallel), and Ulysses
attention, and train data-parallel through DistributedOptimizer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg
from horovod_tpu.models import TransformerLM, lm_loss
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh

VOCAB, B, T = 64, 2, 64
CFG = dict(vocab_size=VOCAB, num_layers=2, num_heads=8, d_model=64,
           d_ff=128, max_seq_len=256, dtype=jnp.float32)


def _tokens(seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, VOCAB, (B, T)).astype(np.int32))


def _init(attention, tokens, seq_axis=None):
    """Model + params; params are backend-independent (same structure)."""
    model = TransformerLM(attention=attention, seq_axis=seq_axis, **CFG)
    variables = model.clone(attention="dense", seq_axis=None).init(
        jax.random.PRNGKey(0), tokens[:, :8])
    return model, variables


def test_forward_shape_and_dtype(hvd):
    tokens = _tokens()
    model, variables = _init("dense", tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (B, T, VOCAB)
    assert logits.dtype == jnp.float32


def test_flash_matches_dense(hvd):
    """The Pallas kernel (interpret mode on CPU) must agree with the
    reference dense path."""
    tokens = _tokens()
    dense_m, variables = _init("dense", tokens)
    flash_m = TransformerLM(attention="flash", **CFG)
    ref = dense_m.apply(variables, tokens)
    out = flash_m.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("backend", ["ring", "ulysses"])
def test_sequence_parallel_matches_dense(hvd, backend):
    """Sharding the sequence over 8 devices must reproduce the dense logits
    (ring: shard-major rotation; ulysses: head re-sharding all_to_all)."""
    tokens = _tokens()
    dense_m, variables = _init("dense", tokens)
    ref = dense_m.apply(variables, tokens)

    sp_model = TransformerLM(attention=backend, seq_axis="data", **CFG)
    mesh = data_parallel_mesh()

    def fwd(variables, tokens_shard, positions_shard):
        return sp_model.apply(variables, tokens_shard, positions_shard)

    # sequence axis sharded: [B, T] -> per-shard [B, T/8]; shard-major
    # positions supplied explicitly
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    out = jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(None, DATA_AXIS), P(None, DATA_AXIS)),
        out_specs=P(None, DATA_AXIS)))(variables, tokens, positions)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def _train_losses(model, mesh, axis_name, tokens, data_spec, steps,
                  positions=None):
    """Shared DistributedOptimizer training loop over a mesh."""
    _, variables = _init(model.attention, tokens, seq_axis=model.seq_axis)
    opt = hvd_pkg.DistributedOptimizer(optax.adam(1e-2), axis_name=axis_name)
    opt_state = opt.init(variables)
    args = (tokens,) if positions is None else (tokens, positions)

    def step(variables, opt_state, *args):
        def loss_fn(v):
            return lm_loss(model.apply(v, *args), args[0])

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        updates, opt_state = opt.update(grads, opt_state, variables)
        return (optax.apply_updates(variables, updates), opt_state,
                jax.lax.pmean(loss, axis_name))

    jitted = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P()) + (data_spec,) * len(args),
        out_specs=(P(), P(), P())))
    losses = []
    for _ in range(steps):
        variables, opt_state, loss = jitted(variables, opt_state, *args)
        losses.append(float(loss))
    return losses


def test_dp_training_loss_decreases(hvd):
    """End-to-end: DistributedOptimizer over the mesh, loss must drop."""
    rng = np.random.default_rng(1)
    # learnable structure: fixed repeating pattern
    seq = np.tile(np.arange(8), (8, T // 8 + 1))[:, :T].astype(np.int32)
    tokens = jnp.asarray(seq + rng.integers(0, 2, (8, T)))
    losses = _train_losses(TransformerLM(**CFG), data_parallel_mesh(),
                           DATA_AXIS, tokens, P(DATA_AXIS), steps=15)
    assert losses[-1] < losses[0] * 0.7, losses


def test_invalid_backend_rejected(hvd):
    tokens = _tokens()
    model = TransformerLM(attention="nope", **CFG)
    variables = TransformerLM(**CFG).init(jax.random.PRNGKey(0),
                                          tokens[:, :8])
    with pytest.raises(ValueError, match="attention must be one of"):
        model.apply(variables, tokens)


def test_ring_requires_seq_axis(hvd):
    tokens = _tokens()
    model = TransformerLM(attention="ring", **CFG)
    variables = TransformerLM(**CFG).init(jax.random.PRNGKey(0),
                                          tokens[:, :8])
    with pytest.raises(ValueError, match="requires seq_axis"):
        model.apply(variables, tokens)


def test_dp_sp_composition(hvd):
    """2-D mesh (docs/long-context.md): batch over 'data' (2), sequence
    over 'seq' (4); ring attention per seq group; DistributedOptimizer
    averages over both axes. Must train."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
    rng = np.random.default_rng(3)
    seq = np.tile(np.arange(8), (4, T // 8)).astype(np.int32)
    tokens = jnp.asarray(seq + rng.integers(0, 2, (4, T)))
    positions = jnp.broadcast_to(jnp.arange(T), tokens.shape)
    losses = _train_losses(
        TransformerLM(attention="ring", seq_axis="seq", **CFG), mesh,
        ("data", "seq"), tokens, P("data", "seq"), steps=12,
        positions=positions)
    assert losses[-1] < losses[0] * 0.8, losses


def test_remat_matches_plain():
    """remat=True must be a pure memory/FLOP trade: identical logits and
    gradients, activations recomputed in backward instead of stored."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models.transformer import TransformerLM, lm_loss

    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, 64)
    kw = dict(vocab_size=64, num_layers=2, num_heads=2, d_model=32,
              d_ff=64, max_seq_len=64, dtype=jnp.float32)
    plain = TransformerLM(**kw)
    remat = TransformerLM(remat=True, **kw)
    params = plain.init(jax.random.PRNGKey(1), tokens)

    def loss_of(model):
        return lambda p: lm_loss(model.apply(p, tokens), tokens)

    # the flag must be observable, not just numerically equivalent: the
    # grad jaxpr of the remat model carries checkpoint (remat) equations,
    # the plain one does not — otherwise silently dropping nn.remat would
    # keep this test green while losing the memory trade it exists for
    jaxpr_r = str(jax.make_jaxpr(jax.grad(loss_of(remat)))(params))
    jaxpr_p = str(jax.make_jaxpr(jax.grad(loss_of(plain)))(params))
    assert "remat" in jaxpr_r, "remat=True produced no checkpoint eqns"
    assert "remat" not in jaxpr_p

    lp, gp = jax.value_and_grad(loss_of(plain))(params)
    lr, gr = jax.value_and_grad(loss_of(remat))(params)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-6)
    flat_p = jax.tree_util.tree_leaves(gp)
    flat_r = jax.tree_util.tree_leaves(gr)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
