"""Generation-ordered sub-buffer flush tests (docs/tensor-fusion.md).

The overlap tentpole's battery: generation-ordering units, bit-exactness
of subbuffered vs single-flush worlds on both negotiation cores, the
donation HLO scan, sentry/consensus interplay with multiple flushes per
step, and chaos delay under overlap. Named to sort past the 870 s tier-1
truncation point (ROADMAP operational note), like test_metrics/
test_tracing/test_tune; multi-step soaks live under ``slow``.
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.ops.engine import (  # noqa: E402
    TensorTableEntry,
    _FlushClock,
    cut_generations,
)
from horovod_tpu.ops.messages import RequestType  # noqa: E402


def _entries(sizes):
    return [TensorTableEntry(name=f"t{i}", op=RequestType.ALLREDUCE,
                             array=np.zeros((n,), np.float32), handle=i)
            for i, n in enumerate(sizes)]


# -- generation ordering ------------------------------------------------------

def test_cut_generations_preserves_arrival_order_and_partition():
    entries = _entries([8] * 10)
    for n in (1, 2, 3, 4, 10):
        chunks = cut_generations(entries, n)
        assert len(chunks) == n
        assert all(chunks), "no chunk may be empty"
        # the concatenation IS the input: contiguous, no reordering —
        # negotiated execution order must stay the arrival order
        flat = [e for chunk in chunks for e in chunk]
        assert [e.name for e in flat] == [e.name for e in entries]


def test_cut_generations_balances_by_bytes():
    # one huge early tensor must not drag the whole tick into chunk 0
    entries = _entries([100_000, 10, 10, 10])
    chunks = cut_generations(entries, 2)
    assert [e.name for e in chunks[0]] == ["t0"]
    assert [e.name for e in chunks[1]] == ["t1", "t2", "t3"]
    # equal sizes split down the middle
    chunks = cut_generations(_entries([64] * 6), 2)
    assert [len(c) for c in chunks] == [3, 3]


def test_cut_generations_edges():
    assert cut_generations([], 4) == []
    one = _entries([16])
    assert cut_generations(one, 4) == [one]  # never more chunks than entries
    many = _entries([16] * 3)
    assert [len(c) for c in cut_generations(many, 8)] == [1, 1, 1]
    assert cut_generations(many, 1) == [many]


def test_flush_clock_busy_accounting():
    import time

    clock = _FlushClock()
    assert clock.busy_seconds() == 0.0
    clock.mark_start()
    time.sleep(0.02)
    open_busy = clock.busy_seconds()  # open interval counts
    assert open_busy > 0.0
    clock.mark_end()
    closed = clock.busy_seconds()
    assert closed >= open_busy
    assert clock.busy_seconds() == closed  # idle: frozen


# -- config / knob plumbing ---------------------------------------------------

def test_subbuffers_config_parse(monkeypatch):
    from horovod_tpu.core.config import Config

    monkeypatch.delenv("HOROVOD_FUSION_SUBBUFFERS", raising=False)
    cfg = Config.from_env()
    assert cfg.fusion_subbuffers == 1
    assert not cfg.fusion_subbuffers_explicit
    monkeypatch.setenv("HOROVOD_FUSION_SUBBUFFERS", "4")
    cfg = Config.from_env()
    assert cfg.fusion_subbuffers == 4
    assert cfg.fusion_subbuffers_explicit  # pinned for the autotuner
    monkeypatch.setenv("HOROVOD_FUSION_SUBBUFFERS", "0")
    assert Config.from_env().fusion_subbuffers == 1  # clamped, never 0


def test_flush_ordinal_desync_fails_loudly():
    from horovod_tpu.ops.controller import ControllerService
    from horovod_tpu.ops.messages import RequestList

    check = ControllerService._check_flush_ordinals
    aligned = {0: RequestList(rank=0, flush_ordinal=3),
               1: RequestList(rank=1, flush_ordinal=3)}
    check(None, aligned, ("cycle", 3))  # aligned: no error
    legacy = {0: RequestList(rank=0), 1: RequestList(rank=1)}
    check(None, legacy, ("cycle", 7))  # pre-field wires: skipped
    # the check is RELATIVE: fresh tooling clients restart their counts
    # against a persistent service, symmetrically — not a desync
    check(None, aligned, ("cycle", 9))
    desynced = {0: RequestList(rank=0, flush_ordinal=3),
                1: RequestList(rank=1, flush_ordinal=4)}
    with pytest.raises(RuntimeError, match="cycle stream desync.*rank"):
        check(None, desynced, ("cycle", 3))


# -- donation HLO scan --------------------------------------------------------

def test_reduce_donation_lands_in_hlo():
    """The in-place flush claim, audited: the compiled fused-reduction
    program must alias its donated input bucket to the output
    (input_output_alias in the module header) — without it sub-buffer
    churn would hold input + output buckets live per flush."""
    from horovod_tpu.ops.xla_plane import XlaDataPlane

    plane = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
    hlo = plane.reduce_donation_hlo(5000)
    assert "input_output_alias" in hlo, hlo[:400]
    # the quantized wire's reduction donates too
    hlo_q = plane.reduce_donation_hlo(5000, codec="int8")
    assert "input_output_alias" in hlo_q, hlo_q[:400]


# -- multi-process worlds -----------------------------------------------------

def _world_fn(steps, n_tensors):
    """Per-rank body: step-dependent accumulator pinning final state
    bit-exactly, plus pipeline/integrity stats."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    acc = np.zeros((32,), np.float64)
    for step in range(steps):
        handles = [
            hvd.allreduce_async(
                np.full((32,), float((rank + 1) * (i + 1) * (step + 1)),
                        np.float32),
                average=False, name=f"sb.{i}")
            for i in range(n_tensors)]
        for i, h in enumerate(handles):
            out = np.asarray(hvd.synchronize(h))
            np.testing.assert_array_equal(
                out, float(sum((r + 1) * (i + 1) * (step + 1)
                               for r in range(size))))
            acc += out.astype(np.float64) * (i + 2)
    eng = get_engine()
    overlap = eng.overlap_stats()
    integrity = eng.integrity_stats()
    client = eng._client
    chaos = getattr(client, "_chaos", None)
    events = list(chaos.events) if chaos is not None else []
    hvd.shutdown()
    return {"rank": rank, "acc": float(acc.sum()), "overlap": overlap,
            "sentry": integrity["sentry"],
            "consensus_windows": integrity["consensus_windows"],
            "chaos_events": events}


def _run_world(np_, steps=5, n_tensors=6, **env):
    from horovod_tpu.runner import run

    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2", **env}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        return run(_world_fn, args=(steps, n_tensors), np=np_,
                   timeout_s=180.0, start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize("native_core", ["0", "1"])
def test_mp_subbuffered_bit_exact_vs_single_flush(native_core):
    """The acceptance pin: subbuffers=2 is bit-exact against the
    single-flush baseline on BOTH negotiation cores, with real measured
    overlap and a depth-2 pipeline; the default config runs the
    single-flush path with zero pipeline activity."""
    base = {"HOROVOD_NATIVE_CONTROLLER": "0",
            "HOROVOD_NATIVE_CORE": native_core}
    single = _run_world(2, HOROVOD_FUSION_SUBBUFFERS="1", **base)
    piped = _run_world(2, HOROVOD_FUSION_SUBBUFFERS="2", **base)
    assert sorted(r["acc"] for r in single) == \
        sorted(r["acc"] for r in piped)
    for r in single:
        assert not r["overlap"]["pipelined"], r
        assert r["overlap"]["flushes"] == 0, r
    for r in piped:
        ov = r["overlap"]
        assert ov["pipelined"] and ov["subbuffers"] == 2, r
        assert ov["overlap_seconds"] > 0, r
        assert ov["inflight_peak"] >= 2, r
        assert ov["flushes"] > 0, r


def test_mp_sentry_consensus_with_multiple_flushes_per_step():
    """Integrity interplay (docs/integrity.md): with several flushes per
    step the sentry's collective verdict exchange and the consensus
    digest windows stay keyed to the negotiated batch stream — every
    batch screened exactly once, windows complete, zero false trips,
    results exact."""
    steps, n_tensors, subbuffers = 5, 7, 3
    results = _run_world(
        2, steps=steps, n_tensors=n_tensors,
        HOROVOD_NATIVE_CONTROLLER="0",
        HOROVOD_FUSION_SUBBUFFERS=str(subbuffers),
        HOROVOD_GRAD_SENTRY="skip",
        HOROVOD_CONSENSUS_INTERVAL_STEPS="2")
    for r in results:
        assert r["overlap"]["pipelined"], r
        assert r["sentry"]["collective"], r  # the real-wire OR-fold ran
        assert r["sentry"]["trips"] == [], r
        # every flushed batch was screened: sub-buffering multiplies
        # batches per step but must never skip (or double-screen) one
        assert r["sentry"]["checks"] == r["overlap"]["flushes"], r
        assert r["consensus_windows"] >= 2, r
    assert results[0]["sentry"]["checks"] == \
        results[1]["sentry"]["checks"]


def test_mp_chaos_delay_under_overlap():
    """A deterministic delay on rank 1's cycle channel under depth-2
    pipelining: the world completes with exact results (the delayed
    negotiation just shrinks the overlap window, never correctness) and
    the injection is rank-scoped. Odd period per the PR-6 soak lesson."""
    results = _run_world(
        2, HOROVOD_NATIVE_CONTROLLER="0",
        HOROVOD_FUSION_SUBBUFFERS="2",
        HOROVOD_CHAOS="delay@rank1:20ms:every3")
    accs = {r["acc"] for r in results}
    assert len(accs) == 1, results
    faulted = [r for r in results if r["rank"] == 1][0]
    assert any(kind == "delay" for kind, _ in faulted["chaos_events"]), \
        results
    clean = [r for r in results if r["rank"] == 0][0]
    assert not clean["chaos_events"], results
    for r in results:
        assert r["overlap"]["pipelined"], r


def test_mp_native_controller_degrades_to_single_flush():
    """The native controller's binary wire predates the data-channel
    hello: HOROVOD_FUSION_SUBBUFFERS degrades deterministically to the
    single-flush path (warned once), results stay exact."""
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native controller unavailable: {cc.load_error()}")
    results = _run_world(2, HOROVOD_NATIVE_CONTROLLER="1",
                         HOROVOD_FUSION_SUBBUFFERS="2")
    for r in results:
        assert not r["overlap"]["pipelined"], r
        assert r["overlap"]["subbuffers"] == 1, r  # the degrade landed
        assert r["overlap"]["flushes"] == 0, r


def test_size1_world_degrades_and_tuned_knob_is_safe(monkeypatch):
    """Size-1 worlds negotiate in-process — nothing to overlap: the knob
    degrades at init, and a tuned-knob retune arriving later (the
    autotune piggyback path) degrades identically instead of arming a
    half-world pipeline."""
    monkeypatch.setenv("HOROVOD_FUSION_SUBBUFFERS", "2")
    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    try:
        eng = get_engine()
        assert eng._flush_worker is None
        assert eng._subbuffers == 1
        out = hvd.allreduce(np.full((64,), 3.0, np.float32),
                            average=False)
        np.testing.assert_array_equal(np.asarray(out), 3.0)
        # the tuning plane's piggyback: same degrade, no crash
        msg = types.SimpleNamespace(tuned_knobs={"fusion_subbuffers": 4})
        eng._apply_tuned_knobs(msg)
        assert eng._flush_worker is None
        assert eng._subbuffers == 1
    finally:
        hvd.shutdown()


@pytest.mark.slow
def test_mp_subbuffer_soak_deep_pipeline():
    """Multi-step soak: depth-4 pipeline with sentry + consensus armed
    for many steps, bit-exact against single-flush."""
    base = {"HOROVOD_NATIVE_CONTROLLER": "0",
            "HOROVOD_GRAD_SENTRY": "skip",
            "HOROVOD_CONSENSUS_INTERVAL_STEPS": "3"}
    single = _run_world(2, steps=30, n_tensors=9,
                        HOROVOD_FUSION_SUBBUFFERS="1", **base)
    piped = _run_world(2, steps=30, n_tensors=9,
                       HOROVOD_FUSION_SUBBUFFERS="4", **base)
    assert sorted(r["acc"] for r in single) == \
        sorted(r["acc"] for r in piped)
    for r in piped:
        assert r["overlap"]["inflight_peak"] >= 2, r
        assert r["sentry"]["trips"] == [], r


@pytest.mark.slow
def test_dryrun_overlap_certification():
    """The driver-facing certification end to end, as __main__ runs it."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_overlap(); "
         "print('dryrun_overlap OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=580)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "dryrun_overlap OK" in result.stdout, result.stdout
