"""Hierarchical collectives, sparse gradients, checkpoint helpers
(reference: hierarchical allreduce ``operations.cc:1284-1436``, sparse path
``tensorflow/__init__.py:72-83``, checkpoint conventions SURVEY §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import (
    hierarchical_allgather,
    hierarchical_allreduce,
    hierarchical_grad_allreduce,
)


def _mesh_2d():
    devs = np.asarray(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("dcn", "ici"))


def test_hierarchical_allreduce_matches_flat(hvd):
    mesh = _mesh_2d()
    x = jnp.arange(32.0, dtype=jnp.float32)  # (4,) per shard

    def flat(xs):
        return jax.lax.pmean(xs, ("dcn", "ici"))

    def hier(xs):
        return hierarchical_allreduce(xs, "dcn", "ici", average=True)

    got_flat = jax.jit(shard_map(flat, mesh=mesh, in_specs=P(("dcn", "ici")),
                                 out_specs=P()))(x)
    got_hier = jax.jit(shard_map(hier, mesh=mesh, in_specs=P(("dcn", "ici")),
                                 out_specs=P(("dcn", "ici"))))(x)
    # hierarchical keeps per-shard layout; every shard holds the mean slice
    np.testing.assert_allclose(np.asarray(got_hier),
                               np.tile(np.asarray(got_flat), 8), rtol=1e-6)


def test_hierarchical_allgather_rank_order(hvd):
    mesh = _mesh_2d()
    x = jnp.arange(8.0, dtype=jnp.float32).reshape(8, 1)

    def gather(xs):
        return hierarchical_allgather(xs, "dcn", "ici")[None]

    out = jax.jit(shard_map(gather, mesh=mesh, in_specs=P(("dcn", "ici")),
                            out_specs=P(("dcn", "ici"))))(x)
    # every shard sees all 8 values; ici-major then dcn ordering preserves
    # global rank order for a (dcn, ici)-major mesh layout
    for shard in np.asarray(out).reshape(8, 8):
        assert sorted(shard.tolist()) == list(range(8))


def test_hierarchical_grad_allreduce_padding(hvd):
    mesh = _mesh_2d()
    # 7 elements: not divisible by ici=4, exercises the pad path
    grads = {"w": jnp.ones((8, 7), dtype=jnp.float32)}

    def step(g):
        return hierarchical_grad_allreduce(g, "dcn", "ici", average=True)

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                            out_specs=P(("dcn", "ici"))))(grads)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0, rtol=1e-6)


def test_distributed_optimizer_hierarchical(hvd):
    mesh = _mesh_2d()
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), axis_name=("dcn", "ici"),
                                   hierarchical=True)
    grads_per_shard = jnp.arange(8.0, dtype=jnp.float32)

    def step(g):
        params = jnp.zeros((1,))
        state = opt.init(params)
        updates, _ = opt.update(g, state, params)
        return updates

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P(("dcn", "ici")),
                            out_specs=P(("dcn", "ici"))))(grads_per_shard)
    np.testing.assert_allclose(np.asarray(out), -3.5, rtol=1e-6)


def test_sparse_allreduce_eager(hvd):
    slices = hvd.IndexedSlices(
        indices=np.array([0, 2], dtype=np.int64),
        values=np.array([[1.0, 1.0], [2.0, 2.0]], dtype=np.float32),
        dense_shape=(4, 2))
    out = hvd.allreduce_sparse(slices, average=False, name="sp")
    dense = np.asarray(out.to_dense())
    expected = np.zeros((4, 2), np.float32)
    expected[0] = 1.0
    expected[2] = 2.0
    np.testing.assert_array_equal(dense, expected)


def test_sparse_allreduce_spmd_duplicates_sum(hvd):
    mesh = hvd.parallel.data_parallel_mesh()
    # every shard contributes a slice at row 1 -> to_dense sums 8 copies
    values = jnp.ones((8, 1, 2), dtype=jnp.float32)
    indices = jnp.ones((8, 1), dtype=jnp.int32)

    def step(v, i):
        s = hvd.allreduce_sparse(
            hvd.IndexedSlices(i[0], v[0], (4, 2)), average=False,
            axis_name="data")
        return s.to_dense()[None]

    out = jax.jit(shard_map(step, mesh=mesh,
                            in_specs=(P("data"), P("data")),
                            out_specs=P("data")))(values, indices)
    for shard in np.asarray(out):
        np.testing.assert_array_equal(shard[1], 8.0)
        np.testing.assert_array_equal(shard[0], 0.0)


def test_checkpoint_save_restore_roundtrip(hvd, tmp_path):
    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": np.asarray(7)}
    path = str(tmp_path / "ckpt")
    hvd.checkpoint.save(path, state)
    restored = hvd.checkpoint.restore(path)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert int(np.asarray(restored["step"])) == 7
