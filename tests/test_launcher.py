"""Launcher tests (reference: ``test/test_spark.py:41-110`` — happy path
with per-rank results, fast failure on a broken command, failure
propagation when a rank dies)."""

import os
import sys

import pytest

from horovod_tpu.runner import LaunchError, launch, run

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_mp_worker.py")


def test_launch_allreduce_world():
    rc = launch([sys.executable, _WORKER, "allreduce"], np=2,
                host_data_plane=True)
    assert rc == 0


def test_launch_propagates_rank_failure():
    with pytest.raises(LaunchError) as excinfo:
        launch([sys.executable, "-c",
                "import os, sys; sys.exit(3 if os.environ['HOROVOD_RANK'] == '1' else 0)"],
               np=2)
    assert excinfo.value.rank == 1
    assert excinfo.value.returncode == 3


def test_launch_missing_binary_fails_fast():
    with pytest.raises(FileNotFoundError):
        launch(["definitely-not-a-real-binary-xyz"], np=2)


def test_launch_error_names_rank_code_and_stderr_tail():
    """A dead worker's LaunchError must carry the failed rank, its exit
    code, and the tail of its captured stderr — not surface later as an
    opaque result-wait timeout."""
    with pytest.raises(LaunchError) as excinfo:
        launch([sys.executable, "-c",
                "import os, sys\n"
                "if os.environ['HOROVOD_RANK'] == '1':\n"
                "    print('boom: synthetic worker crash', file=sys.stderr)\n"
                "    sys.exit(7)\n"
                "import time; time.sleep(30)\n"],
               np=2, capture_stderr=True, job_timeout_s=60.0)
    err = excinfo.value
    assert err.rank == 1 and err.returncode == 7
    assert "boom: synthetic worker crash" in str(err)
    assert "code 7" in str(err)


def test_launch_controller_listener_is_prebound():
    """TOCTOU fix: rank 0 receives the launcher's LIVE listening socket
    (HOROVOD_CONTROLLER_FD) on the advertised controller port."""
    probe = (
        "import os, socket\n"
        "fd = int(os.environ['HOROVOD_CONTROLLER_FD'])\n"
        "s = socket.socket(fileno=fd)\n"
        "port = s.getsockname()[1]\n"
        "assert port == int(os.environ['HOROVOD_CONTROLLER_PORT']), port\n"
        "s.listen(128)\n"  # already listening: re-listen is a no-op\n
        "s.close()\n"
    )
    rc = launch([sys.executable, "-c", probe], np=1, job_timeout_s=60.0)
    assert rc == 0


def test_launch_allreduce_world_python_controller_adopts_fd():
    """End to end on the Python controller service: rank 0's
    ControllerService must adopt the inherited listener (no rebind) and
    the world must still negotiate and reduce correctly."""
    rc = launch([sys.executable, _WORKER, "allreduce"], np=2,
                host_data_plane=True, job_timeout_s=120.0,
                env_extra={"HOROVOD_NATIVE_CONTROLLER": "0"})
    assert rc == 0


def _silent_exit_fn():
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 1:
        os._exit(0)  # dies without reporting a result, exit code 0
    hvd.shutdown()
    return "ok"


def test_run_fn_names_silent_exit_instead_of_timing_out():
    """A worker that exits 0 WITHOUT registering a result used to eat the
    whole result timeout; now the driver names the silent ranks as soon
    as the launcher observes every process gone."""
    import time

    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as excinfo:
        run(_silent_exit_fn, np=2, timeout_s=300.0)
    assert "without reporting a result" in str(excinfo.value)
    assert "[1]" in str(excinfo.value)
    assert time.monotonic() - t0 < 120.0


def _worker_fn(scale):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.full(3, float(hvd.rank() + 1), np.float32),
                        average=False, name="runfn.sum")
    total = float(np.asarray(out)[0])
    return {"rank": hvd.rank(), "sum": total, "scaled": hvd.rank() * scale}


def test_run_fn_collects_rank_results():
    results = run(_worker_fn, args=(10,), np=2, timeout_s=120.0)
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["sum"] == 3.0 for r in results)  # 1 + 2
    assert [r["scaled"] for r in results] == [0, 10]


def _failing_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 1:
        raise RuntimeError("intentional rank failure")
    return "ok"


def test_run_fn_propagates_worker_exception():
    with pytest.raises((RuntimeError, LaunchError)) as excinfo:
        run(_failing_fn, np=2, timeout_s=120.0)
    assert "rank 1" in str(excinfo.value) or "intentional" in str(excinfo.value)


def test_horovodrun_cli():
    import subprocess

    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--host-data-plane", sys.executable, _WORKER, "broadcast"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert rc.returncode == 0, rc.stderr


def test_parse_hosts():
    from horovod_tpu.runner.launcher import parse_hosts

    assert parse_hosts("a:2,b:3") == [("a", 2), ("b", 3)]
    assert parse_hosts("solo") == [("solo", 1)]
    with pytest.raises(ValueError):
        parse_hosts("a:x")
    with pytest.raises(ValueError):
        parse_hosts("a:0")
    with pytest.raises(ValueError):
        parse_hosts("")


def test_launch_hosts_topology():
    """-H localhost:2,localhost:2 = a 2x2 virtual cluster: global ranks
    0..3, local ranks 0..1 per entry, cross ranks 0..1 (the comm-split
    structure of ``operations.cc:1760-1797``), with a real allreduce."""
    from horovod_tpu.runner.launcher import launch_hosts

    probe = (
        "import os, sys, json\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(2, np.float32), average=False,\n"
        "                    name='mh.sum')\n"
        "assert float(np.asarray(out)[0]) == 4.0, np.asarray(out)\n"
        "expect_local = hvd.rank() % 2\n"
        "expect_cross = hvd.rank() // 2\n"
        "assert hvd.local_rank() == expect_local, (hvd.rank(), hvd.local_rank())\n"
        "assert hvd.local_size() == 2\n"
        "assert hvd.cross_rank() == expect_cross, (hvd.rank(), hvd.cross_rank())\n"
        "assert hvd.cross_size() == 2\n"
        "hvd.shutdown()\n"
    )
    rc = launch_hosts([sys.executable, "-c", probe],
                      [("localhost", 2), ("localhost", 2)],
                      host_data_plane=True, job_timeout_s=120.0)
    assert rc == 0


def test_launch_hosts_rsh_agent(tmp_path):
    """A custom rsh agent (mpirun's plm_rsh_agent hook, the seam the
    reference's Spark integration uses — ``spark/driver/mpirun_rsh.py``)
    must be invoked once per rank with the host and the env-wrapped
    command, and the job must still work end to end."""
    from horovod_tpu.runner.launcher import launch_hosts

    log = tmp_path / "rsh_calls"
    agent = tmp_path / "fake_rsh.py"
    agent.write_text(
        "#!/usr/bin/env python\n"
        "import subprocess, sys\n"
        f"open({str(log)!r}, 'a').write(sys.argv[1] + '\\n')\n"
        "host, remote = sys.argv[1], sys.argv[2]\n"
        "sys.exit(subprocess.call(['bash', '-c', remote]))\n")
    probe = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(1, np.float32), average=False, name='r')\n"
        "assert float(np.asarray(out)[0]) == 2.0\n"
        "hvd.shutdown()\n"
    )
    rc = launch_hosts(
        [sys.executable, "-c", probe], [("localhost", 1), ("localhost", 1)],
        rsh_agent=[sys.executable, str(agent)],
        controller_addr="127.0.0.1",
        host_data_plane=True, job_timeout_s=120.0)
    assert rc == 0
    calls = log.read_text().splitlines()
    assert calls == ["localhost", "localhost"]


def test_launch_hosts_remote_simulation(tmp_path):
    """A simulated REMOTE 2x2 world: hosts named by hostname (not
    localhost), so the launcher must derive the controller address from
    hosts[0], export a non-loopback controller bind for rank 0, and
    forward world env + env_extra through the rsh line — the fake rsh
    scrubs its inherited environment the way a real ssh session would
    start clean (ADVICE round-1 items + reference
    ``spark/util/network.py:117-141`` NIC advertisement)."""
    import socket

    from horovod_tpu.runner.launcher import launch_hosts

    hostname = socket.gethostname()
    try:
        socket.gethostbyname(hostname)
    except OSError:
        pytest.skip("hostname does not resolve locally")

    agent = tmp_path / "fake_rsh.py"
    agent.write_text(
        "#!/usr/bin/env python\n"
        "import os, subprocess, sys\n"
        "# simulate a clean remote login shell: only the env assignments\n"
        "# embedded in the remote command line may carry the world\n"
        "env = {k: v for k, v in os.environ.items()\n"
        "       if not k.startswith(('HOROVOD_', 'HVD_TEST_'))}\n"
        "sys.exit(subprocess.call(['bash', '-c', sys.argv[2]], env=env))\n")
    probe = (
        "import os\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "assert os.environ.get('HVD_TEST_EXTRA') == '42', 'env_extra lost'\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(2, np.float32), average=False,\n"
        "                    name='remote.sum')\n"
        "assert float(np.asarray(out)[0]) == 4.0, np.asarray(out)\n"
        "hvd.shutdown()\n"
    )
    rc = launch_hosts(
        [sys.executable, "-c", probe],
        [(hostname, 2), (hostname, 2)],
        rsh_agent=[sys.executable, str(agent)],
        env_extra={"HVD_TEST_EXTRA": "42"},
        host_data_plane=True, job_timeout_s=180.0)
    assert rc == 0


def test_horovodrun_cli_hosts():
    import subprocess

    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-H",
         "localhost:2", "--host-data-plane",
         sys.executable, _WORKER, "allreduce"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert rc.returncode == 0, rc.stderr


def test_horovodrun_cli_np_and_hosts_conflict():
    import subprocess

    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "-H",
         "localhost:2", sys.executable, "-c", "pass"],
        capture_output=True, text=True, timeout=60)
    assert rc.returncode != 0
    assert "exactly one of" in rc.stderr


def test_build_rank_env_pins_tpu_chip_per_slot():
    """Several slots on one host -> one chip per process (the TPU analog
    of the reference's one-GPU-per-process model: the runtime locks chips
    to the first process that initializes them, so the pin must come from
    the launcher env, not user code)."""
    from horovod_tpu.runner.launcher import build_rank_env

    env = build_rank_env(5, 8, 1234, "s", base_env={}, local_rank=1,
                         local_size=4)
    assert env["TPU_VISIBLE_DEVICES"] == "1"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
    # one process per host (the TPU-native model): all chips stay visible
    env1 = build_rank_env(0, 4, 1234, "s", base_env={}, local_rank=0,
                          local_size=1)
    assert "TPU_VISIBLE_DEVICES" not in env1
    # explicit user topology wins over the launcher's default pin
    env2 = build_rank_env(0, 4, 1234, "s",
                          base_env={"TPU_PROCESS_BOUNDS": "2,2,1"},
                          local_rank=0, local_size=4)
    assert "TPU_VISIBLE_DEVICES" not in env2
    assert env2["TPU_PROCESS_BOUNDS"] == "2,2,1"
    # documented opt-out
    env3 = build_rank_env(
        0, 4, 1234, "s",
        base_env={"HOROVOD_LAUNCHER_PIN_DEVICES": "0"},
        local_rank=0, local_size=4)
    assert "TPU_VISIBLE_DEVICES" not in env3
    # programmatic env_extra merges BEFORE the pin: the opt-out and user
    # topology passed via launch(env_extra=...) must also be honored
    env4 = build_rank_env(
        0, 4, 1234, "s", base_env={}, local_rank=0, local_size=4,
        env_extra={"HOROVOD_LAUNCHER_PIN_DEVICES": "0"})
    assert "TPU_VISIBLE_DEVICES" not in env4
    env5 = build_rank_env(
        0, 4, 1234, "s", base_env={}, local_rank=0, local_size=4,
        env_extra={"TPU_PROCESS_BOUNDS": "2,2,1"})
    assert "TPU_VISIBLE_DEVICES" not in env5
    assert env5["TPU_PROCESS_BOUNDS"] == "2,2,1"


def test_cli_example_composition():
    """The documented user flow, end to end: the CLI launcher driving a
    real example across 2 ranks (the exact command in
    examples/pytorch_mnist.py's header), steered onto CPU via
    HOROVOD_PLATFORM — the knob exists because JAX_PLATFORMS alone cannot
    keep workers off a TPU plugin that prepends itself to the list."""
    import subprocess

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["HOROVOD_PLATFORM"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--host-data-plane", sys.executable,
         os.path.join(root, "examples", "pytorch_mnist.py"),
         "--epochs", "1"],
        cwd=root, env=env, capture_output=True, text=True, timeout=420)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "epoch 0: loss=" in result.stdout


def test_rsh_wrap_forwards_pin_and_steering_vars():
    """Remote workers must receive the chip pin and platform steering —
    they are part of the world description, not local-only state."""
    from horovod_tpu.runner.launcher import _rsh_wrap, build_rank_env

    env = build_rank_env(1, 4, 1234, "s", base_env={"HOROVOD_PLATFORM": "cpu"},
                         local_rank=1, local_size=4)
    argv = _rsh_wrap(["ssh"], "remotehost", env, ["python", "train.py"])
    remote = argv[-1]
    assert "TPU_VISIBLE_DEVICES=1" in remote
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS=1,1,1" in remote
    assert "TPU_PROCESS_BOUNDS=1,1,1" in remote
    assert "HOROVOD_PLATFORM=cpu" in remote
