"""Launcher tests (reference: ``test/test_spark.py:41-110`` — happy path
with per-rank results, fast failure on a broken command, failure
propagation when a rank dies)."""

import os
import sys

import pytest

from horovod_tpu.runner import LaunchError, launch, run

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_mp_worker.py")


def test_launch_allreduce_world():
    rc = launch([sys.executable, _WORKER, "allreduce"], np=2,
                host_data_plane=True)
    assert rc == 0


def test_launch_propagates_rank_failure():
    with pytest.raises(LaunchError) as excinfo:
        launch([sys.executable, "-c",
                "import os, sys; sys.exit(3 if os.environ['HOROVOD_RANK'] == '1' else 0)"],
               np=2)
    assert excinfo.value.rank == 1
    assert excinfo.value.returncode == 3


def test_launch_missing_binary_fails_fast():
    with pytest.raises(FileNotFoundError):
        launch(["definitely-not-a-real-binary-xyz"], np=2)


def _worker_fn(scale):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.full(3, float(hvd.rank() + 1), np.float32),
                        average=False, name="runfn.sum")
    total = float(np.asarray(out)[0])
    return {"rank": hvd.rank(), "sum": total, "scaled": hvd.rank() * scale}


def test_run_fn_collects_rank_results():
    results = run(_worker_fn, args=(10,), np=2, timeout_s=120.0)
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["sum"] == 3.0 for r in results)  # 1 + 2
    assert [r["scaled"] for r in results] == [0, 10]


def _failing_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 1:
        raise RuntimeError("intentional rank failure")
    return "ok"


def test_run_fn_propagates_worker_exception():
    with pytest.raises((RuntimeError, LaunchError)) as excinfo:
        run(_failing_fn, np=2, timeout_s=120.0)
    assert "rank 1" in str(excinfo.value) or "intentional" in str(excinfo.value)


def test_horovodrun_cli():
    import subprocess

    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--host-data-plane", sys.executable, _WORKER, "broadcast"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert rc.returncode == 0, rc.stderr
