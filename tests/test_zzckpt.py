"""Checkpoint plane (docs/checkpoint.md).

Named ``test_zz*`` past the 870 s tier-1 truncation point on purpose
(the PR 11–16 convention): the ledger/journal/committer units are cheap,
but the kill-mid-commit worlds each spawn 2-process elastic runs.

Coverage per the ISSUE-17 battery: seal-ledger semantics (world digest
vote, chunk completeness, digest disagreement, epoch fence, monotonic
watermark, disk spill/reload with torn-spill refusal), ticket-journal
durability, the async committer (fault grammar, latest-wins
supersession, chunked wire roundtrip against a REAL ElasticService),
``State`` integration (commit cadence knob, push-timeout satellite,
sealed restore provenance), the train-to-serve hot swap, the
wire-compat registry, the metrics-summary section, and — slow tier —
the kill-mid-commit chaos cells on both negotiation cores plus the
2-proc ``dryrun_ckpt`` certification.
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.ckpt.committer import AsyncCommitter, parse_ckpt_fault
from horovod_tpu.ckpt.store import SealLedger, TicketJournal
from horovod_tpu.core.config import (
    HOROVOD_CKPT_CHUNK_BYTES,
    HOROVOD_CKPT_INTERVAL_STEPS,
    HOROVOD_CKPT_PUSH_TIMEOUT_S,
    HOROVOD_ELASTIC_ADDR,
    HOROVOD_ELASTIC_PORT,
)
from horovod_tpu.integrity.consensus import digest_bytes, tree_digest

pytestmark = pytest.mark.ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- seal ledger ---------------------------------------------------------------


def _feed(ledger, ckpt_no, tree, world=2, epoch=0, ranks=None,
          chunk_bytes=64):
    """Stream one commit into the ledger the way the wire would: rank 0
    ships chunks, every rank votes the tree digest. Returns the payload."""
    payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
    digest = tree_digest(tree)
    meta = {"commit_no": ckpt_no, "world": world}
    sealed = -1
    for rank in (ranks if ranks is not None else range(world)):
        ledger.ingest_begin(epoch, ckpt_no, rank, meta)
    n_chunks = max((len(payload) + chunk_bytes - 1) // chunk_bytes, 1)
    for seq in range(n_chunks):
        ledger.ingest_chunk(epoch, ckpt_no, 0, seq,
                            payload[seq * chunk_bytes:(seq + 1) * chunk_bytes])
    for rank in (ranks if ranks is not None else range(world)):
        sealed = ledger.ingest_end(epoch, ckpt_no, rank, n_chunks, digest)
    return payload, sealed


def test_seal_requires_every_ranks_digest_vote():
    ledger = SealLedger()
    tree = {"w": np.arange(8, dtype=np.float32), "step": 3}
    # only rank 0 of a world of 2 reported: no seal
    payload, sealed = _feed(ledger, 1, tree, world=2, ranks=[0])
    assert sealed == -1
    assert ledger.fetch_sealed() == (-1, {}, None)
    # rank 1's vote arrives: seals, bit-exact, digest-stamped meta
    n_chunks = max((len(payload) + 63) // 64, 1)
    sealed = ledger.ingest_end(0, 1, 1, 0, tree_digest(tree))
    assert sealed == 1
    no, meta, got = ledger.fetch_sealed()
    assert (no, got) == (1, payload)
    assert meta["digest"] == tree_digest(tree)
    assert meta["world"] == 2
    restored = pickle.loads(got)
    np.testing.assert_array_equal(restored["w"], tree["w"])
    assert n_chunks > 1  # the feed really was a multi-chunk stream


def test_missing_chunk_never_seals():
    ledger = SealLedger()
    tree = {"w": np.zeros(64, np.float32)}
    payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
    ledger.ingest_begin(0, 1, 0, {"commit_no": 1, "world": 1})
    ledger.ingest_chunk(0, 1, 0, 0, payload[:64])  # chunk 1 lost
    sealed = ledger.ingest_end(0, 1, 0, 2, tree_digest(tree))
    assert sealed == -1
    assert ledger.stats()["partials"] == [1]


def test_digest_disagreement_never_seals_and_counts():
    from horovod_tpu.obs.registry import registry

    def mismatches():
        fam = registry().snapshot().get(
            "horovod_ckpt_digest_mismatches_total")
        return sum(s["value"] for s in fam["samples"]) if fam else 0

    before = mismatches()
    ledger = SealLedger()
    tree = {"w": np.ones(4, np.float32)}
    payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
    for rank in range(2):
        ledger.ingest_begin(0, 1, rank, {"commit_no": 1, "world": 2})
    ledger.ingest_chunk(0, 1, 0, 0, payload)
    ledger.ingest_end(0, 1, 0, 1, tree_digest(tree))
    sealed = ledger.ingest_end(0, 1, 1, 0, "divergent-digest")
    assert sealed == -1
    assert mismatches() == before + 1
    # the poisoned partial is dropped, not retried into a seal
    assert ledger.stats()["partials"] == []


def test_epoch_fence_and_monotonic_watermark():
    ledger = SealLedger()
    tree = {"step": 1}
    _, sealed = _feed(ledger, 2, tree, world=1)
    assert sealed == 2
    # a ghost stream from a previous epoch is acknowledged and ignored
    _, sealed = _feed(ledger, 5, {"step": 99}, world=1, epoch=7)
    assert sealed == 2
    # a commit at or below the watermark is history
    _, sealed = _feed(ledger, 2, {"step": 88}, world=1)
    assert sealed == 2
    assert pickle.loads(ledger.fetch_sealed()[2]) == {"step": 1}


def test_begin_epoch_drops_partials_keeps_sealed_and_journal():
    ledger = SealLedger()
    _feed(ledger, 1, {"step": 1}, world=1)
    ledger.journal.put("req-1", {"state": "pending"})
    # a partial (world 2, only rank 0 voted) is mid-flight when the
    # world dies
    _feed(ledger, 2, {"step": 2}, world=2, ranks=[0])
    assert ledger.stats()["partials"] == [2]
    ledger.begin_epoch(1)
    assert ledger.stats() == {"sealed_no": 1, "partials": [], "epoch": 1}
    assert ledger.journal.get("req-1") == {"state": "pending"}
    # the NEW epoch's streams are admitted under the fence
    _, sealed = _feed(ledger, 2, {"step": 2}, world=1, epoch=1)
    assert sealed == 2


def test_spill_reload_bit_exact_and_torn_spill_refused(tmp_path):
    d = str(tmp_path / "ledger")
    ledger = SealLedger(dir=d)
    tree = {"w": np.arange(256, dtype=np.float32), "step": 9}
    payload, sealed = _feed(ledger, 3, tree, world=1)
    assert sealed == 3
    # a fresh ledger (driver restart) reloads the sealed commit
    reloaded = SealLedger(dir=d)
    no, meta, got = reloaded.fetch_sealed()
    assert (no, got) == (3, payload)
    np.testing.assert_array_equal(pickle.loads(got)["w"], tree["w"])
    # tear the spilled bytes: the reload must refuse, not restore garbage
    path = os.path.join(d, "ckpt-3.bin")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    torn = SealLedger(dir=d)
    assert torn.fetch_sealed() == (-1, {}, None)


def test_on_seal_hook_fires_with_sealed_commit():
    seals = []
    ledger = SealLedger(
        on_seal=lambda no, meta, payload: seals.append((no, meta, payload)))
    payload, sealed = _feed(ledger, 1, {"step": 1}, world=1)
    assert sealed == 1
    assert len(seals) == 1
    no, meta, got = seals[0]
    assert (no, got) == (1, payload)
    assert meta["digest"] == tree_digest({"step": 1})


# -- ticket journal ------------------------------------------------------------


def test_ticket_journal_roundtrip_cap_and_persistence(tmp_path):
    d = str(tmp_path / "journal")
    journal = TicketJournal(dir=d, max_entries=3)
    for i in range(5):
        journal.put(f"req-{i}", {"state": "pending", "i": i})
    # drop-oldest cap: only the 3 freshest survive
    assert sorted(journal.entries()) == ["req-2", "req-3", "req-4"]
    assert journal.get("req-0") is None
    journal.delete("req-3")
    assert journal.get("req-3") is None
    # a fresh journal (driver restart) reloads from disk
    reloaded = TicketJournal(dir=d, max_entries=3)
    assert sorted(reloaded.entries()) == ["req-2", "req-4"]
    assert reloaded.get("req-4") == {"state": "pending", "i": 4}


# -- async committer -----------------------------------------------------------


def test_parse_ckpt_fault_grammar():
    assert parse_ckpt_fault("") is None
    assert parse_ckpt_fault("0:2") == (0, 2, 0)  # chunk defaults to 0
    assert parse_ckpt_fault("1:3:4") == (1, 3, 4)
    # malformed specs parse to None (the elastic-twin convention: a typo
    # must not take down production jobs)
    assert parse_ckpt_fault("nope") is None
    assert parse_ckpt_fault("a:b:c") is None
    assert parse_ckpt_fault("1:2:3:4") is None


def test_committer_latest_wins_supersession():
    committer = AsyncCommitter(("127.0.0.1", 9), rank=0, world=1,
                               secret=b"k")
    streamed = []
    gate = threading.Event()
    started = threading.Event()

    def slow_stream(ckpt_no, tree, epoch):
        streamed.append(ckpt_no)
        started.set()
        gate.wait(timeout=10.0)

    committer._stream = slow_stream
    try:
        committer.submit(1, {"step": 1}, 0)
        assert started.wait(timeout=10.0)
        # while commit 1 is still streaming, 2 is superseded by 3:
        # latest-wins, never a convoy
        committer.submit(2, {"step": 2}, 0)
        committer.submit(3, {"step": 3}, 0)
        gate.set()
        assert committer.wait_idle(timeout_s=10.0)
        assert streamed == [1, 3]
    finally:
        committer.close()


@pytest.fixture()
def elastic_service():
    from horovod_tpu.elastic.health import ElasticService
    from horovod_tpu.runner.network import make_secret

    secret = bytes.fromhex(make_secret())
    service = ElasticService(secret, heartbeat_interval_s=1.0,
                             miss_limit=1000)
    yield service, secret
    service.shutdown()


def test_async_commit_chunked_wire_roundtrip(elastic_service):
    service, secret = elastic_service
    addr = ("127.0.0.1", service.port)
    tree = {"w": np.arange(1024, dtype=np.float32), "step": 5}
    committers = [AsyncCommitter(addr, rank=r, world=2, secret=secret,
                                 chunk_bytes=1024) for r in range(2)]
    try:
        for r, c in enumerate(committers):
            c.submit(1, tree, 0)
        deadline = time.monotonic() + 30.0
        while service.ckpt.stats()["sealed_no"] < 1:
            assert time.monotonic() < deadline, service.ckpt.stats()
            time.sleep(0.05)
    finally:
        for c in committers:
            assert c.wait_idle(timeout_s=30.0)
            c.close()
    no, meta, payload = service.ckpt.fetch_sealed()
    assert no == 1
    assert meta["digest"] == tree_digest(tree)
    restored = pickle.loads(payload)
    np.testing.assert_array_equal(restored["w"], tree["w"])
    assert restored["step"] == 5
    # 4 KiB payload over a 1 KiB chunk knob really streamed in chunks
    assert len(payload) > 4096


def test_journal_rpcs_over_wire(elastic_service):
    from horovod_tpu.runner.network import BasicClient

    service, secret = elastic_service
    client = BasicClient(("127.0.0.1", service.port), secret=secret,
                         attempts=3, timeout_s=10.0)
    try:
        assert client.request(("ckpt_journal_put", "req-1",
                               {"state": "pending"})) == ("ok",)
        assert client.request(("ckpt_journal_get", "req-1")) == \
            ("entry", {"state": "pending"})
        assert client.request(("ckpt_journal_del", "req-1")) == ("ok",)
        assert client.request(("ckpt_journal_get", "req-1")) == \
            ("entry", None)
    finally:
        client.close()


# -- State integration ---------------------------------------------------------


def test_state_maybe_commit_interval(hvd, monkeypatch):
    from horovod_tpu.elastic import State

    monkeypatch.delenv(HOROVOD_ELASTIC_PORT, raising=False)
    monkeypatch.setenv(HOROVOD_CKPT_INTERVAL_STEPS, "3")
    state = State(w=np.zeros(2, np.float32), step=0)
    ran = [state.maybe_commit() for _ in range(7)]
    assert ran == [False, False, True, False, False, True, False]
    assert state._commit_no == 2
    # flush on the synchronous path is a no-op that reports drained
    assert state.flush_commits()


def test_push_timeout_knob_reaches_both_clients(elastic_service,
                                                monkeypatch):
    from horovod_tpu.elastic import State

    service, secret = elastic_service
    monkeypatch.setenv(HOROVOD_CKPT_PUSH_TIMEOUT_S, "7.5")
    monkeypatch.setenv(HOROVOD_ELASTIC_ADDR, "127.0.0.1")
    monkeypatch.setenv(HOROVOD_ELASTIC_PORT, str(service.port))
    monkeypatch.setenv("HOROVOD_SECRET_KEY", secret.hex())
    state = State(w=np.zeros(2, np.float32), step=0)
    client = state._store_client()
    try:
        assert client is not None and client._timeout_s == 7.5
    finally:
        state._drop_store_client()
    committer = AsyncCommitter(("127.0.0.1", service.port), rank=0,
                               world=1, secret=secret)
    try:
        assert committer._timeout_s == 7.5
    finally:
        committer.close()


def test_state_restores_sealed_commit_with_provenance(hvd, elastic_service,
                                                      monkeypatch):
    from horovod_tpu.elastic import State

    service, secret = elastic_service
    monkeypatch.setenv(HOROVOD_ELASTIC_ADDR, "127.0.0.1")
    monkeypatch.setenv(HOROVOD_ELASTIC_PORT, str(service.port))
    monkeypatch.setenv("HOROVOD_SECRET_KEY", secret.hex())
    tree = {"w": np.arange(16, dtype=np.float32) * 3.0, "step": 4}
    committer = AsyncCommitter(("127.0.0.1", service.port), rank=0,
                               world=1, secret=secret)
    try:
        committer.submit(4, tree, 0)
        assert committer.wait_idle(timeout_s=30.0)
    finally:
        committer.close()
    assert service.ckpt.stats()["sealed_no"] == 4
    state = State(w=np.zeros(16, np.float32), step=0)
    state.sync()
    assert state.restore_source == "sealed"
    assert state.restore_commit_no == 4
    assert state.step == 4
    np.testing.assert_array_equal(np.asarray(state.w), tree["w"])


def test_state_refuses_sealed_commit_with_wrong_keys(hvd, elastic_service,
                                                     monkeypatch):
    from horovod_tpu.elastic import State

    service, secret = elastic_service
    monkeypatch.setenv(HOROVOD_ELASTIC_ADDR, "127.0.0.1")
    monkeypatch.setenv(HOROVOD_ELASTIC_PORT, str(service.port))
    monkeypatch.setenv("HOROVOD_SECRET_KEY", secret.hex())
    committer = AsyncCommitter(("127.0.0.1", service.port), rank=0,
                               world=1, secret=secret)
    try:
        committer.submit(1, {"other": 1}, 0)
        assert committer.wait_idle(timeout_s=30.0)
    finally:
        committer.close()
    state = State(w=np.zeros(4, np.float32), step=0)
    state.sync()
    # wrong key set: the stored commit is ignored, constructor state wins
    assert state.restore_source is None
    assert state.step == 0


# -- train-to-serve hot swap ---------------------------------------------------


def test_hot_swap_single_worker_old_or_new_never_torn():
    from horovod_tpu.serving import ServingPlane
    from horovod_tpu.serving.worker import serve_worker

    w_old = np.eye(4, dtype=np.float32)
    w_new = np.eye(4, dtype=np.float32) * 2.0
    plane = ServingPlane(gateway_port=None, batch_max=2, slo_ms=10000.0,
                         deadline_ms=30000.0, reconnect_window_s=2.0)
    plane.begin_epoch(0, 1)
    stats_box = []

    def _worker():
        weights = {"w": np.array(w_old)}
        stats_box.append(serve_worker(
            {"m": lambda x: x @ weights["w"]},
            addr=("127.0.0.1", plane.service_port), secret=plane.secret,
            rank=0, size=1, epoch=0, jit=False,
            on_weights=lambda v, tree: weights.update(tree)))

    worker = threading.Thread(target=_worker, daemon=True)
    worker.start()
    try:
        deadline = time.monotonic() + 30.0
        while not plane.stats()["armed"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        x = np.arange(4, dtype=np.float32)
        t1 = plane.submit("m", x, deadline_s=20.0)
        t1.wait(20.0)
        assert t1.state == "done"
        np.testing.assert_array_equal(np.asarray(t1.output), x @ w_old)
        plane.publish_weights(1, tree={"w": np.array(w_new)})
        t2 = plane.submit("m", x, deadline_s=20.0)
        t2.wait(20.0)
        assert t2.state == "done"
        # strictly after the swap ack the result is the NEW weights —
        # old-or-new atomically, and here provably new
        np.testing.assert_array_equal(np.asarray(t2.output), x @ w_new)
        assert plane.stats()["weights_version"] == 1
        assert plane.stats()["swap_pending"] is None
    finally:
        plane.stop()
        worker.join(timeout=30.0)
        plane.close()
    assert stats_box and stats_box[0]["swaps"] == 1
    assert stats_box[0]["weights_version"] == 1
    assert stats_box[0]["outcome"] == "stopped"


def test_publish_weights_refuses_nothing_but_counts_and_arms_pending():
    from horovod_tpu.serving import ServingPlane

    plane = ServingPlane(gateway_port=None, batch_max=2)
    try:
        plane.begin_epoch(0, 2)
        plane.publish_weights(7, tree={"w": [1, 2, 3]})
        stats = plane.stats()
        # no worker acked yet: pending, not applied
        assert stats["swap_pending"] == 7
        assert stats["weights_version"] is None
        # a newer publish supersedes the pending one wholesale
        plane.publish_weights(8, tree={"w": [4]})
        assert plane.stats()["swap_pending"] == 8
    finally:
        plane.close()


# -- registries / knobs / tooling ----------------------------------------------


def test_wire_registry_names_every_ckpt_tag_with_degrade():
    from horovod_tpu.analysis.wire_registry import (
        ELASTIC_RPC_TAGS,
        SERVING_RPC_TAGS,
    )

    for tag in ("ckpt_begin", "ckpt_chunk", "ckpt_end", "ckpt_fetch",
                "ckpt_journal_put", "ckpt_journal_get",
                "ckpt_journal_del"):
        assert tag in ELASTIC_RPC_TAGS
        assert ELASTIC_RPC_TAGS[tag].strip()
    assert "swap_ack" in SERVING_RPC_TAGS
    assert SERVING_RPC_TAGS["swap_ack"].strip()


def test_wire_lint_clean_on_ckpt_and_serving_services():
    from horovod_tpu.analysis.base import load_tree
    from horovod_tpu.analysis.wire import run as wire_run

    modules = load_tree(REPO, ["horovod_tpu"])
    findings = [f for f in wire_run(REPO, modules)
                if "ckpt" in f.key or "ServingPlane" in f.key
                or "ElasticService" in f.key]
    assert findings == [], [f.message for f in findings]


def test_ckpt_interval_knob_ladder():
    from horovod_tpu.tune.policy import KNOB_CKPT_INTERVAL, \
        ckpt_interval_knob

    knob = ckpt_interval_knob(5)
    assert knob.name == KNOB_CKPT_INTERVAL
    assert knob.current == 5.0
    assert not knob.pinned
    assert {1.0, 10.0, 100.0} <= set(knob.values)
    # the live value splices into the ladder even off-candidate
    off = ckpt_interval_knob(7, explicit=True)
    assert off.current == 7.0 and off.pinned


def test_checkpoint_shim_is_single_implementation():
    import horovod_tpu.checkpoint as legacy
    import horovod_tpu.ckpt.files as files

    assert legacy.save is files.save
    assert legacy.restore is files.restore


def test_metrics_summary_renders_checkpoint_section(tmp_path):
    from horovod_tpu.obs.registry import registry

    from horovod_tpu.ckpt import committer as _c

    _c.observe_commit_stall(0.001)
    snap = registry().snapshot()
    assert "horovod_ckpt_commit_stall_seconds" in snap, sorted(snap)
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(snap))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "metrics_summary.py"), str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "checkpoint plane" in proc.stdout
    assert "horovod_ckpt_commit_stall_seconds" in proc.stdout


def test_flightrec_declares_ckpt_events():
    from horovod_tpu.obs import flightrec

    assert flightrec.EV_CKPT_SUBMIT == "ckpt_submit"
    assert flightrec.EV_CKPT_SEAL == "ckpt_seal"
    assert flightrec.EV_CKPT_RESTORE == "ckpt_restore"
    assert flightrec.EV_SERVING_SWAP == "serving_swap"


# -- kill-mid-commit chaos cells (2-proc elastic worlds) -----------------------


def test_chaos_kill_before_commit_restores_sealed():
    from horovod_tpu.chaos.matrix import run_checkpoint_cell

    cell = run_checkpoint_cell("1:2", "", "recovered")
    assert cell["outcome"] == "recovered", cell
    assert cell["restore_no"] == 1, cell


def test_chaos_kill_between_chunks_restores_sealed():
    from horovod_tpu.chaos.matrix import run_checkpoint_cell

    cell = run_checkpoint_cell("", "0:2:1", "recovered")
    assert cell["outcome"] == "recovered", cell
    assert cell["restore_no"] == 1, cell  # the partial stream never sealed


@pytest.mark.slow
@pytest.mark.parametrize("native_core", [0, 1])
def test_chaos_checkpoint_grid_full_sweep(native_core):
    """The full grid on BOTH negotiation cores (the commit stream rides
    the elastic service wire, which must be core-independent)."""
    from horovod_tpu.chaos.matrix import CHECKPOINT_GRID, \
        run_checkpoint_cell

    for elastic_fault, ckpt_fault, expect in CHECKPOINT_GRID:
        cell = run_checkpoint_cell(elastic_fault, ckpt_fault, expect,
                                   native_core=native_core)
        assert cell["outcome"] == expect, cell


@pytest.mark.slow
def test_dryrun_ckpt_certification():
    sys.path.insert(0, REPO)
    try:
        from __graft_entry__ import dryrun_ckpt
    finally:
        sys.path.remove(REPO)
    dryrun_ckpt()
