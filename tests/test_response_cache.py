"""Response-cache (steady-state negotiation bypass) tests.

docs/response-cache.md: unit coverage of the deterministic LRU and its
invalidation edges (capacity eviction, capacity-0 disable, codec-switch
identity misses, elastic epoch stamping), live ControllerService coverage
of the all-hit ack fast path on BOTH negotiation cores, the
fusion-threshold-flip generation bump (autotuner interplay regression),
and multi-process acceptance: bit-exact cached vs uncached allreduce,
timeline counters for the bypass, and a stall injected during an all-hit
steady state still escalating to RanksAbortedError.
"""

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.ops.controller import (
    ControllerClient,
    ControllerService,
    Negotiator,
)
from horovod_tpu.ops.messages import (
    CacheHitAck,
    CacheRequest,
    DataType,
    Request,
    RequestList,
    RequestType,
    ResponseList,
    ResponseType,
    Response,
)
from horovod_tpu.ops.response_cache import (
    ResponseCache,
    bits_of,
    positions_of,
    request_identity,
)

SECRET = b"s" * 32
_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_mp_worker.py")


def _req(name, shape=(8,), codec="none", rank=0):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=tuple(shape), root_rank=-1, codec=codec)


def _resp(*names):
    return Response(ResponseType.ALLREDUCE, tensor_names=list(names),
                    tensor_dtype=DataType.FLOAT32, payload_bytes=32)


def _rl(responses, generation=0, shutdown=False):
    return ResponseList(responses=responses, shutdown=shutdown,
                        cache_generation=generation)


# -- unit: deterministic LRU + invalidation edges -----------------------------

def test_bitvector_roundtrip():
    cap = 1024  # the default knob: a 128-byte wire payload
    positions = [0, 7, 8, 63, 500, 1023]
    bits = bits_of(positions, cap)
    assert len(bits) == cap // 8
    assert positions_of(bits) == positions
    assert positions_of(bits_of([], cap)) == []


def test_hit_requires_exact_batch_cover():
    cache = ResponseCache(8, epoch=0)
    cache.insert_cycle({"a": _req("a"), "b": _req("b")}, [_resp("a", "b")])
    assert cache.plan_cycle([_req("a")]) is None  # partial batch: no replay
    assert cache.plan_cycle([_req("a"), _req("b")]) == [0]
    assert cache.plan_cycle([]) == []  # idle tick: trivially covered


def test_identity_misses_on_shape_dtype_codec_change():
    cache = ResponseCache(8, epoch=0)
    cache.insert_cycle({"g": _req("g")}, [_resp("g")])
    assert cache.plan_cycle([_req("g")]) == [0]
    # HOROVOD_COMPRESSION switch: the codec is part of the identity, so the
    # quantized resubmission MISSES (renegotiates) instead of replaying a
    # full-precision program
    assert cache.plan_cycle([_req("g", codec="int8")]) is None
    assert cache.plan_cycle([_req("g", shape=(16,))]) is None


def test_capacity_zero_disables_cleanly():
    cache = ResponseCache(0)
    assert not cache.enabled
    cache.insert_cycle({"a": _req("a")}, [_resp("a")])
    assert len(cache) == 0
    assert cache.plan_cycle([_req("a")]) is None
    cache.accept_response_list(_rl([_resp("a")]), {"a": _req("a")})
    assert len(cache) == 0


def test_lru_eviction_at_capacity():
    cache = ResponseCache(2, epoch=0)
    for name in ("a", "b", "c"):
        cache.insert_cycle({name: _req(name)}, [_resp(name)])
    assert len(cache) == 2
    assert cache.plan_cycle([_req("a")]) is None  # oldest evicted
    assert cache.plan_cycle([_req("b")]) is not None
    assert cache.plan_cycle([_req("c")]) is not None
    # "c" reused "a"'s slot: positions stay inside the fixed bitvector
    assert all(p < 2 for p in cache.plan_cycle([_req("b"), _req("c")]))
    # a touch (the ack path) refreshes recency: "b" survives the next insert
    cache.touch(cache.plan_cycle([_req("b")]))
    cache.insert_cycle({"d": _req("d")}, [_resp("d")])
    assert cache.plan_cycle([_req("b")]) is not None
    assert cache.plan_cycle([_req("c")]) is None


def test_epoch_stamps_generation_namespace():
    # An elastic relaunch (HOROVOD_ELASTIC_EPOCH bump) starts every cache
    # in a fresh generation namespace: nothing stamped by epoch 0 can
    # validate against epoch 1 state, however many autotune bumps happened.
    g0 = ResponseCache(4, epoch=0).generation
    g1 = ResponseCache(4, epoch=1).generation
    assert g1 > g0
    assert g1 - g0 == 1 << 32
    stale = ResponseCache(4, epoch=0)
    stale.insert_cycle({"a": _req("a")}, [_resp("a")])
    ack = CacheHitAck(positions=[0], generation=g1)
    stale.accept_ack(ack)  # replay still valid, then clear + adopt
    assert stale.generation == g1 and len(stale) == 0


def test_generation_mismatch_clears_and_skips_insert():
    cache = ResponseCache(4, epoch=0)
    cache.insert_cycle({"a": _req("a")}, [_resp("a")])
    # a bumped-generation list clears and does NOT cache its (pre-bump
    # planned) responses; the next matching list repopulates
    cache.accept_response_list(_rl([_resp("b")], generation=7),
                               {"b": _req("b")})
    assert cache.generation == 7 and len(cache) == 0
    cache.accept_response_list(_rl([_resp("b")], generation=7),
                               {"b": _req("b")})
    assert cache.plan_cycle([_req("b")]) is not None


def test_shutdown_and_error_responses_never_cached():
    cache = ResponseCache(4, epoch=0)
    cache.accept_response_list(_rl([_resp("a")], shutdown=True),
                               {"a": _req("a")})
    assert len(cache) == 0
    err = Response(ResponseType.ERROR, tensor_names=["x"],
                   error_message="boom")
    cache.insert_cycle({"x": _req("x")}, [err])
    assert len(cache) == 0


def test_refused_against_cacheless_coordinator():
    # pre-cache coordinator (native wire / capacity 0 there): the stamped
    # generation is None and the rank side must not keep planning bypasses
    cache = ResponseCache(4, epoch=0)
    cache.accept_response_list(ResponseList(responses=[_resp("a")]),
                               {"a": _req("a")})
    assert len(cache) == 0  # not inserted: nothing to stay coherent with


# -- service level: the all-hit ack on both negotiation cores -----------------

def _make_core(core, size, threshold=1 << 26):
    if core == "python":
        return Negotiator(size, threshold)
    import horovod_tpu.cc as cc

    if not cc.available():
        pytest.skip(f"native core unavailable: {cc.load_error()}")
    return cc.NativeNegotiator(size, threshold)


def _drive_world(service, size, plans, capacity=16):
    """Run ``len(plans)`` lockstep cycles from ``size`` threaded clients;
    ``plans[c]`` is a callable (rank, cycle) -> list[Request]. Returns rank
    0's per-cycle (kind, responses, rx_bytes) observations."""
    observations = []
    errors = []
    barrier = threading.Barrier(size)

    def worker(rank):
        try:
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET, rank=rank)
            cache = ResponseCache(capacity, epoch=0)
            for cycle, plan in enumerate(plans):
                requests = plan(rank, cycle)
                positions = cache.plan_cycle(requests)
                barrier.wait(timeout=60)
                if positions is not None:
                    out = client.cycle(rank, CacheRequest(
                        rank=rank, bits=bits_of(positions, cache.capacity),
                        generation=cache.generation))
                else:
                    out = client.cycle(rank, RequestList(rank=rank,
                                                         requests=requests))
                if isinstance(out, CacheHitAck):
                    responses = cache.accept_ack(out)
                    kind = "ack"
                else:
                    responses = out.responses
                    cache.accept_response_list(
                        out, {r.tensor_name: r for r in requests})
                    kind = "list"
                if rank == 0:
                    observations.append(
                        (kind, [list(r.tensor_names) for r in responses],
                         client.last_cycle_rx_bytes
                         + client.last_cycle_tx_bytes))
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)
            barrier.abort()

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    return observations


@pytest.mark.parametrize("core", ["python", "native"])
def test_all_hit_cycle_returns_compact_ack(core):
    size = 2
    service = ControllerService(size, _make_core(core, size), secret=SECRET,
                                port=0, cache_capacity=16,
                                fusion_threshold_bytes=1 << 26)
    try:
        steady = lambda rank, cycle: [_req(f"t{i}", rank=rank)  # noqa: E731
                                      for i in range(4)]
        obs = _drive_world(service, size, [steady] * 4)
    finally:
        service.shutdown()
    kinds = [k for k, _, _ in obs]
    assert kinds == ["list", "ack", "ack", "ack"], obs
    # the replayed fused batch is the negotiated one, in the same order
    assert obs[1][1] == obs[0][1]
    # the compact ack + bitvector move strictly fewer bytes than the full
    # RequestList/ResponseList round trip — the acceptance criterion
    assert obs[1][2] < obs[0][2], obs
    assert obs[2][2] == obs[1][2]


@pytest.mark.parametrize("core", ["python", "native"])
def test_fusion_threshold_flip_invalidates_mid_run(core):
    """Autotuner interplay regression: set_fusion_threshold mid-run must
    bump the cache generation so ranks renegotiate under the new packing —
    a warm cache must NOT keep replaying the old fused layout."""
    size = 2
    tensor_bytes = 8 * 4  # f32[8]
    service = ControllerService(size, _make_core(core, size),
                                secret=SECRET, port=0, cache_capacity=16,
                                fusion_threshold_bytes=1 << 26)
    flipped = threading.Event()

    def plan(rank, cycle):
        if cycle == 3 and rank == 0 and not flipped.is_set():
            flipped.set()
            # mid-run knob change, between cycles (the autotuner's own
            # calls land inside the cycle; both defer the bump safely)
            service.set_fusion_threshold(tensor_bytes)  # forces splits
        return [_req(f"t{i}", rank=rank) for i in range(4)]

    try:
        obs = _drive_world(service, size, [plan] * 7)
    finally:
        service.shutdown()
    kinds = [k for k, _, _ in obs]
    # warm-up: miss, ack, ack; the flip cycle may still ack (replaying the
    # pre-flip layout one last time is consistent) but must carry the new
    # generation → exactly one renegotiating miss, then acks again
    assert kinds[:3] == ["list", "ack", "ack"], obs
    assert "list" in kinds[3:5], obs
    renegotiated = obs[kinds.index("list", 3)][1]
    assert len(renegotiated) == 4, (
        "threshold flip did not repack: still replaying the old fused "
        "layout", obs)
    assert kinds[-1] == "ack", obs  # and the NEW layout is cached again
    assert obs[-1][1] == renegotiated


def test_capacity_desync_refused_loudly():
    # the bitvector length IS the capacity; a diverged knob must refuse on
    # the ALL-HIT path too (eviction choices diverge → silent misreplay)
    size = 1
    service = ControllerService(size, Negotiator(size, 1 << 26),
                                secret=SECRET, port=0, cache_capacity=16,
                                fusion_threshold_bytes=1 << 26)
    try:
        client = ControllerClient(("127.0.0.1", service.port),
                                  secret=SECRET, rank=0)
        with pytest.raises(Exception, match="capacity desync"):
            client.cycle(0, CacheRequest(rank=0, bits=bytes(4),
                                         generation=0))
        client.close()
    finally:
        service.shutdown()


def test_cacheless_service_refuses_cache_bits_loudly():
    size = 1
    service = ControllerService(size, Negotiator(size, 1 << 26),
                                secret=SECRET, port=0, cache_capacity=0)
    try:
        client = ControllerClient(("127.0.0.1", service.port),
                                  secret=SECRET, rank=0)
        with pytest.raises(Exception, match="HOROVOD_CACHE_CAPACITY"):
            client.cycle(0, CacheRequest(rank=0, bits=b"", generation=0))
        client.close()
    finally:
        service.shutdown()


# -- multi-process acceptance -------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cache_world(scenario, size, extra_env=None, timeout=90.0):
    """Minimal _mp_worker harness (the full battery lives in
    test_multiprocess; these are the cache acceptance runs)."""
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": str(size),
            "HOROVOD_LOCAL_RANK": str(rank), "HOROVOD_LOCAL_SIZE": str(size),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HOROVOD_DATA_PLANE": "host",
            "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0",  # the cache-bit wire
        })
        env.update(extra_env or {})
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, scenario], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"rank {rank} timed out in {scenario!r}")
        assert proc.returncode == 0, (
            f"rank {rank} exited {proc.returncode} in {scenario!r}\n"
            f"stdout:\n{out}\nstderr:\n{err}")
        assert f"WORKER-OK {rank}" in out, (rank, out)
        outs.append(out)
    return outs


def _cache_hashes(outs):
    hashes = [re.search(r"CACHE-HASH (\w+)", out).group(1) for out in outs]
    assert len(set(hashes)) == 1, hashes  # identical on every rank
    return hashes[0]


def test_mp_cached_bit_exact_vs_uncached(tmp_path):
    """The acceptance criterion: cached and uncached runs produce
    bit-identical allreduce results — plus the observability satellite:
    the bypass shows up as timeline counters, not silently."""
    timeline = str(tmp_path / "cache_timeline.json")
    warm = _run_cache_world("cache_steady", 2,
                            extra_env={"HOROVOD_TIMELINE": timeline})
    cold = _run_cache_world("cache_steady", 2,
                            extra_env={"HOROVOD_CACHE_CAPACITY": "0"})
    assert _cache_hashes(warm) == _cache_hashes(cold)

    counters = []
    with open(timeline) as fh:
        for line in fh:
            if '"response_cache"' not in line:
                continue
            counters.append(json.loads(line.rstrip().rstrip(","))["args"])
    assert counters, "bypass ran but emitted no timeline counters"
    last = counters[-1]
    assert last["hit_cycles"] > 0, last
    assert last["miss_cycles"] >= 1, last
    # negotiation bytes/cycle: an ack cycle must be visibly cheaper than a
    # full negotiated cycle in the same trace
    tx = [c["negotiation_tx_bytes"] for c in counters
          if c["negotiation_tx_bytes"] > 0]
    assert min(tx) < max(tx), counters[:5]


def test_mp_stall_during_all_hit_steady_state_still_escalates():
    """Acceptance: HOROVOD_STALL_SHUTDOWN_TIME_S keeps firing when every
    cycle is a cache hit — the hit path still runs the coordinator's stall
    check and ships its warnings, so PR 2's escalation converts the
    planted stall into RanksAbortedError instead of a masked hang."""
    _run_cache_world("cache_stall", 2, timeout=120.0, extra_env={
        "HOROVOD_STALL_WARNING_TIME": "1",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "2",
    })


# -- elastic interplay --------------------------------------------------------

def _elastic_cache_fn(heal_epoch):
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    if world_epoch() < heal_epoch and hvd.rank() == 1:
        os._exit(11)
    for step in range(4):
        out = hvd.allreduce(np.full((8,), 1.0, np.float32), average=False,
                            name="ec.g")
        np.testing.assert_array_equal(np.asarray(out), float(hvd.size()))
    stats = get_engine().cache_stats()
    hvd.shutdown()
    return {"epoch": world_epoch(), "generation": stats["generation"],
            "hits": stats["hit_cycles"]}


def test_elastic_relaunch_epoch_invalidates():
    """Invalidation edge: a relaunched world's caches live in the NEW
    epoch's generation namespace (epoch << 32), so nothing stamped before
    the crash can validate after it — and the relaunched steady state
    still reaches the bypass."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _elastic_cache_fn, args=(1,), np=2, min_np=2, max_restarts=2,
        backoff_s=0.1, timeout_s=120.0, start_timeout_s=120.0,
        heartbeat_interval_s=0.5, heartbeat_miss_limit=6,
        env_extra={"HOROVOD_NATIVE_CONTROLLER": "0",  # the cache-bit wire
                   "HOROVOD_CYCLE_TIME": "2"})
    assert len(results) == 2
    for result in results:
        assert result["epoch"] == 1, results
        assert result["generation"] == 1 << 32, results  # epoch-stamped
        assert result["hits"] > 0, results  # cache live after relaunch
