"""Tier-1 tests for the contract-analysis plane (docs/analysis.md).

Fixture snippets prove each hvdlint checker fires on a deliberately
seeded violation and that each suppression syntax works; the repo
self-check at the bottom is the enforcement: drift in any of the six
contracts fails the suite, not a reviewer. Everything here is AST-only
(no worlds, no subprocesses except the one CLI smoke) — this module
sorts *before* the tier-1 truncation point, so budget matters.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from horovod_tpu.analysis import (
    base,
    collectives,
    errors,
    knobs,
    locks,
    markers,
    metrics_docs,
    runner,
    wire,
    wire_registry,
    witness,
)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mod_from(tmp_path, rel, src):
    """A SourceModule parsed from a fixture snippet."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    out = base.load_module(str(path), str(tmp_path))
    assert out is not None, f"fixture {rel} failed to parse"
    return out


def codes_of(findings):
    return sorted(f.code for f in findings)


# -- knob registry (HVL1xx) ---------------------------------------------------

FAKE_CONFIG = '''
HOROVOD_GOOD = "HOROVOD_GOOD"
HOROVOD_UNDOCUMENTED = "HOROVOD_UNDOCUMENTED"
'''


def test_knob_literal_read_fires_hvl101(tmp_path):
    cfg = mod_from(tmp_path, "horovod_tpu/core/config.py", FAKE_CONFIG)
    bad = mod_from(tmp_path, "horovod_tpu/bad.py", '''
        import os
        x = os.environ.get("HOROVOD_SNEAKY", "")
        y = os.environ["HOROVOD_SUBSCRIPT"]
        z = os.getenv("HOROVOD_GETENV")
    ''')
    found = knobs.check_env_reads([cfg, bad], knobs.declared_knobs(cfg))
    assert codes_of(found) == ["HVL101", "HVL101", "HVL101"]
    assert {f.key.split("@")[0] for f in found} == \
        {"HOROVOD_SNEAKY", "HOROVOD_SUBSCRIPT", "HOROVOD_GETENV"}


def test_knob_undeclared_constant_fires_hvl102_declared_passes(tmp_path):
    cfg = mod_from(tmp_path, "horovod_tpu/core/config.py", FAKE_CONFIG)
    user = mod_from(tmp_path, "horovod_tpu/user.py", '''
        import os
        from .core import config as _config
        ok = os.environ.get(_config.HOROVOD_GOOD, "")
        bad = os.environ.get(_config.HOROVOD_TYPO, "")
    ''')
    found = knobs.check_env_reads([cfg, user], knobs.declared_knobs(cfg))
    assert codes_of(found) == ["HVL102"]
    assert found[0].key.startswith("HOROVOD_TYPO@")


def test_knob_docs_row_fires_hvl103_and_expands_combined_rows(tmp_path):
    cfg = mod_from(tmp_path, "horovod_tpu/core/config.py", FAKE_CONFIG)
    docs = "a knob table row: HOROVOD_GOOD does things"
    found = knobs.check_docs_rows(cfg, docs)
    assert codes_of(found) == ["HVL103"]
    assert found[0].key == "HOROVOD_UNDOCUMENTED"
    # the combined docs idioms all document their siblings
    names = knobs.documented_knob_names(
        "`HOROVOD_ELASTIC_ADDR` / `_PORT` and HOROVOD_RANK/SIZE plus "
        "HOROVOD_HIERARCHICAL_ALLREDUCE/ALLGATHER")
    assert {"HOROVOD_ELASTIC_PORT", "HOROVOD_RANK", "HOROVOD_SIZE",
            "HOROVOD_HIERARCHICAL_ALLGATHER"} <= names


# -- lock order (HVL201) ------------------------------------------------------

def test_lock_cycle_fires_hvl201(tmp_path):
    bad = mod_from(tmp_path, "pkg/deadlock.py", '''
        class S:
            def f(self):
                with self._alock:
                    with self._block:
                        pass
            def g(self):
                with self._block:
                    with self._alock:
                        pass
    ''')
    findings = locks.cycle_findings(locks.module_graph(bad))
    assert codes_of(findings) == ["HVL201"]
    assert "pkg.deadlock:S._alock" in findings[0].message
    assert findings[0].key.startswith("cycle:")


def test_lock_nesting_one_direction_is_clean_and_acquire_pairs(tmp_path):
    ok = mod_from(tmp_path, "pkg/fine.py", '''
        class S:
            def f(self):
                with self._alock:
                    with self._block:
                        pass
            def g(self):
                self._alock.acquire()
                self._block.acquire()
                self._block.release()
                self._alock.release()
    ''')
    graph = locks.module_graph(ok)
    # both paths observe the same a -> b order: one edge, no cycle
    assert list(graph) == [("pkg.fine:S._alock", "pkg.fine:S._block")]
    assert locks.cycle_findings(graph) == []


# -- collective divergence (HVL301) -------------------------------------------

def test_rank_conditional_collective_fires_hvl301(tmp_path):
    bad = mod_from(tmp_path, "pkg/diverge.py", '''
        def step(x):
            if rank() == 0:
                return allreduce(x)
            return x
        class W:
            def push(self, item):
                if self._rank == 0:
                    self._cycles.submit(1, 0, item, None)
    ''')
    found = collectives.scan_module(bad)
    assert codes_of(found) == ["HVL301", "HVL301"]
    assert {f.key for f in found} == {
        "allreduce@pkg/diverge.py:step",
        "self._cycles.submit@pkg/diverge.py:W.push"}


def test_collective_outside_branch_is_clean(tmp_path):
    ok = mod_from(tmp_path, "pkg/fine.py", '''
        def bcast(obj, root_rank):
            if rank() == root_rank:
                payload = encode(obj)   # rank-gated WORK is fine
            else:
                payload = empty()
            return allgather(payload)   # every rank joins
    ''')
    assert collectives.scan_module(ok) == []


def test_inline_suppression_syntaxes_silence_hvl301(tmp_path):
    waived = mod_from(tmp_path, "pkg/waived.py", '''
        def replay(x):
            if rank() == 0:
                broadcast(x, 0)  # hvdlint: disable=HVL301 -- lockstep replay
            if rank() == 1:
                # hvdlint: disable=HVL301 -- standalone comment form
                broadcast(x, 1)
    ''')
    found = collectives.scan_module(waived)
    assert len(found) == 2  # checker fires; the runner applies waivers
    kept = base.apply_inline_suppressions(found, {waived.rel: waived})
    assert kept == []


# -- wire compatibility (HVL4xx) ----------------------------------------------

FAKE_CONTROLLER = '''
class ControllerService:
    def _handle(self, req, sock):
        kind = req[0]
        if kind == "hello":
            return ("ok",)
        if kind == "teleport":
            return ("whoosh",)
'''

FAKE_MESSAGES = '''
from dataclasses import dataclass
@dataclass
class RequestList:
    rank: int
    shiny_new_field: int = 0
@dataclass
class CacheRequest:
    rank: int
'''


def test_unregistered_rpc_tag_and_field_fire_hvl401_hvl402(tmp_path):
    ctrl = mod_from(tmp_path, "pkg/controller.py", FAKE_CONTROLLER)
    msgs = mod_from(tmp_path, "pkg/messages.py", FAKE_MESSAGES)
    registry_rpc = {"hello": "baseline"}
    registry_fields = {"RequestList.rank": "baseline",
                       "CacheRequest.rank": "baseline"}
    found = wire.check(ctrl, msgs, registry_rpc, registry_fields)
    assert codes_of(found) == ["HVL401", "HVL402"]
    assert found[0].key == "rpc:teleport"
    assert found[1].key == "field:RequestList.shiny_new_field"


def test_stale_and_empty_registry_entries_fire_hvl403(tmp_path):
    ctrl = mod_from(tmp_path, "pkg/controller.py", FAKE_CONTROLLER)
    msgs = mod_from(tmp_path, "pkg/messages.py", FAKE_MESSAGES)
    found = wire.check(
        ctrl, msgs,
        {"hello": "", "teleport": "beam", "gone_tag": "was removed"},
        {"RequestList.rank": "x", "RequestList.shiny_new_field": "y",
         "CacheRequest.rank": "z", "CacheRequest.gone": "was removed"})
    assert codes_of(found) == ["HVL403", "HVL403", "HVL403"]
    assert {f.key for f in found} == {
        "empty-rpc:hello", "stale-rpc:gone_tag",
        "stale-field:CacheRequest.gone"}


def test_real_wire_scan_matches_registry_exactly():
    lib = base.load_tree(REPO, ["horovod_tpu"])
    controller = next(m for m in lib
                      if m.rel == "horovod_tpu/ops/controller.py")
    messages = next(m for m in lib
                    if m.rel == "horovod_tpu/ops/messages.py")
    tags = wire.scan_rpc_tags(controller)
    fields = wire.scan_message_fields(messages)
    assert set(tags) == set(wire_registry.RPC_TAGS)
    assert set(fields) == set(wire_registry.MESSAGE_FIELDS)


# -- metrics/docs drift (HVL5xx) ----------------------------------------------

def test_metrics_drift_fires_all_three_codes(tmp_path):
    code = mod_from(tmp_path, "pkg/metrics_user.py", '''
        FAMILY = "horovod_via_constant_total"
        C1 = reg.counter("horovod_documented_total", "help")
        C2 = reg.counter("horovod_undocumented_total", "help")
        C3 = reg.gauge(FAMILY, "help")
    ''')
    fams = metrics_docs.registered_families([code])
    assert "horovod_via_constant_total" in fams  # constant resolved
    docs = metrics_docs.docs_families(
        "| `horovod_documented_total` | counter |\n"
        "| `horovod_via_constant_total` | gauge |\n"
        "| `horovod_ghost_total` | counter |\n")
    prefixes = {"horovod_documented_": 1, "horovod_nothing_matches_": 2}
    found = metrics_docs.check(fams, docs, prefixes)
    assert codes_of(found) == ["HVL501", "HVL502", "HVL503"]
    assert found[0].key == "family:horovod_undocumented_total"
    assert found[1].key == "docs:horovod_ghost_total"
    assert found[2].key == "prefix:horovod_nothing_matches_"


def test_docs_tx_rx_combined_row_documents_both():
    toks = metrics_docs.docs_families(
        "| `horovod_wire_tx/rx_bytes_total` | counter |")
    assert {"horovod_wire_tx_bytes_total",
            "horovod_wire_rx_bytes_total"} <= set(toks)


# -- error taxonomy (HVL6xx) --------------------------------------------------

FAKE_STATUS = '''
class HorovodInternalError(RuntimeError):
    pass

class OrphanError(HorovodInternalError):
    pass

class WiredError(HorovodInternalError):
    pass

def format_wired(x):
    return f"[wired: {x}]"

def parse_wired(msg):
    return None

def format_lonely(x):
    return f"[lonely: {x}]"

class Status:
    def raise_if_error(self):
        w = parse_wired("")
        if w is not None:
            raise WiredError(w)
        raise HorovodInternalError("x")
'''


def test_status_taxonomy_fires_hvl601_and_hvl602(tmp_path):
    status = mod_from(tmp_path, "horovod_tpu/core/status.py", FAKE_STATUS)
    found = errors.check_status(status)
    assert codes_of(found) == ["HVL601", "HVL602"]
    assert found[0].key == "err:OrphanError"  # defined, never re-raised
    assert found[1].key == "tag:format_lonely"  # no parse_ twin


def test_external_subclass_fires_hvl603_unless_registered(tmp_path):
    status = mod_from(tmp_path, "horovod_tpu/core/status.py", FAKE_STATUS)
    ext = mod_from(tmp_path, "horovod_tpu/plane/err.py", '''
        class PlaneError(HorovodInternalError):
            pass
        class KnownError(WiredError):
            pass
    ''')
    names = set(errors.status_subclasses(status))
    found = errors.check_external_subclasses(
        [status, ext], names, {"KnownError": "has a story"})
    assert codes_of(found) == ["HVL603"]
    assert found[0].key == "err:PlaneError@horovod_tpu/plane/err.py"


# -- pytest markers (HVL701) --------------------------------------------------

def test_unregistered_marker_fires_hvl701(tmp_path):
    tests = mod_from(tmp_path, "tests/test_x.py", '''
        import pytest
        @pytest.mark.slow
        @pytest.mark.mystery
        @pytest.mark.parametrize("x", [1])
        def test_a(x):
            pass
    ''')
    pyproject = ('[tool.pytest.ini_options]\nmarkers = [\n'
                 '    "slow: registered",\n]\n')
    found = markers.check([tests], pyproject)
    assert codes_of(found) == ["HVL701"]
    assert found[0].key == "marker:mystery"


# -- baseline machinery (HVL9xx) ----------------------------------------------

def _finding(code="HVL301", key="k1"):
    return base.Finding(code=code, path="x.py", line=1, message="m",
                        key=key)


def test_baseline_waives_matching_finding():
    bl = base.Baseline(entries=[
        {"code": "HVL301", "key": "k1", "reason": "known good"}])
    kept, hygiene, waived = bl.apply([_finding()])
    assert kept == [] and hygiene == [] and waived == 1


def test_reasonless_waiver_fires_hvl902_stale_fires_hvl901():
    bl = base.Baseline(entries=[
        {"code": "HVL301", "key": "k1", "reason": ""},
        {"code": "HVL201", "key": "gone", "reason": "was fixed"}])
    kept, hygiene, waived = bl.apply([_finding()])
    assert kept == [] and waived == 1
    assert codes_of(hygiene) == ["HVL901", "HVL902"]


# -- runtime lock witness -----------------------------------------------------

def test_witness_raises_on_inversion_the_ast_pass_cannot_see(tmp_path):
    # the inverted orders are established through CALL CHAINS — no
    # function lexically nests two acquisitions, so the AST pass finds
    # no edges at all...
    src = '''
        def hold_a_then_b(a, b):
            with a:
                grab(b)
        def hold_b_then_a(a, b):
            with b:
                grab(a)
        def grab(lock):
            with lock:
                pass
    '''
    mod = mod_from(tmp_path, "pkg/chained.py", src)
    assert locks.module_graph(mod) == {}  # blind spot, by design
    # ...while the witness sees the dynamic order and raises at the
    # exact second site
    w = witness.LockWitness()
    a = witness.WitnessedLock(threading.Lock(), "A", w)
    b = witness.WitnessedLock(threading.Lock(), "B", w)

    def grab(lock):
        with lock:
            pass

    with a:
        grab(b)  # establishes A -> B
    with pytest.raises(witness.LockInversionError) as exc:
        with b:
            grab(a)  # B -> A closes the cycle
    assert "A" in str(exc.value) and "B" in str(exc.value)
    assert (("A", "B") in w.edges())
    # the diagnosis must be LOUD, not a wedge: the inversion raises
    # BEFORE the raw grab, so neither lock is left held
    assert not a.locked() and not b.locked()
    with a:  # and the world is still usable afterwards
        pass


def test_witness_allows_consistent_order_and_reentry():
    w = witness.LockWitness()
    a = witness.WitnessedLock(threading.RLock(), "A", w)
    b = witness.WitnessedLock(threading.Lock(), "B", w)
    for _ in range(3):
        with a:
            with a:  # re-entrant same-lock grab is not an inversion
                with b:
                    pass
    assert (("A", "B") in w.edges())


def test_reasonless_or_typod_inline_suppression_is_loud_not_silent(
        tmp_path):
    # built by concatenation so THIS file's own hygiene scan (the repo
    # self-check) does not see a literal malformed suppression comment
    marker = "# hvdlint: " + "disable="
    src = (
        "def f(x):\n"
        "    if rank() == 0:\n"
        f"        allreduce(x)  {marker}HVL301\n"      # no reason
        f"        allgather(x)  {marker}HVL310 -- typo'd code\n")
    path = tmp_path / "pkg" / "noisy.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    mod = base.load_module(str(path), str(tmp_path))
    found = collectives.scan_module(mod)
    # neither malformed suppression suppresses its finding...
    kept = base.apply_inline_suppressions(found, {mod.rel: mod})
    assert codes_of(kept) == ["HVL301", "HVL301"]
    # ...and both are findings in their own right
    hygiene = mod.suppression_hygiene()
    assert codes_of(hygiene) == ["HVL903", "HVL904"]


def test_lock_pass_sees_assign_and_condition_form_acquires(tmp_path):
    bad = mod_from(tmp_path, "pkg/trylock.py", '''
        class S:
            def f(self):
                got = self._alock.acquire(timeout=5)
                with self._block:
                    pass
            def g(self):
                if self._block.acquire(False):
                    with self._alock:
                        pass
    ''')
    findings = locks.cycle_findings(locks.module_graph(bad))
    assert codes_of(findings) == ["HVL201"]


def test_witness_reentrant_grab_while_holding_later_lock_is_legal():
    # `with a: with b: with a:` is globally consistent — re-acquiring an
    # owned RLock can never deadlock, so it must not read as B -> A
    w = witness.LockWitness()
    a = witness.WitnessedLock(threading.RLock(), "A", w)
    b = witness.WitnessedLock(threading.Lock(), "B", w)
    with a:
        with b:
            with a:
                pass
    assert ("B", "A") not in w.edges()


def test_witness_failed_trylock_records_no_order():
    # the trylock-with-backoff idiom: a non-blocking acquire that FAILS
    # established no order and must not condemn the later reverse grab
    w = witness.LockWitness()
    a = witness.WitnessedLock(threading.Lock(), "A", w)
    b = witness.WitnessedLock(threading.Lock(), "B", w)
    b._lock.acquire()  # someone else owns B
    with a:
        assert b.acquire(blocking=False) is False
    assert ("A", "B") not in w.edges()
    b._lock.release()
    with b:  # the reverse order is the first REAL order — legal
        with a:
            pass


def test_inline_suppression_does_not_leak_to_the_next_line(tmp_path):
    mod = mod_from(tmp_path, "pkg/leak.py", '''
        def f(x):
            if rank() == 0:
                allreduce(x)  # hvdlint: disable=HVL301 -- this one only
                allgather(x)
    ''')
    found = collectives.scan_module(mod)
    kept = base.apply_inline_suppressions(found, {mod.rel: mod})
    # the waiver covers its own line; the next line's finding survives
    assert [f.key for f in kept] == ["allgather@pkg/leak.py:f"]


def test_rpc_scan_handles_membership_dispatch(tmp_path):
    ctrl = mod_from(tmp_path, "pkg/controller.py", '''
        class ControllerService:
            def _handle(self, req, sock):
                kind = req[0]
                if kind in ("metrics", "metrics_pull"):
                    return ("ok",)
    ''')
    assert set(wire.scan_rpc_tags(ctrl)) == {"metrics", "metrics_pull"}


def test_hvl502_catches_one_sided_rename_but_allows_prefix_mentions():
    fams = {"horovod_sentry_checks_total": ("x.py", 1)}
    docs = {"horovod_sentry_checks": 3,   # rename drift: must fire
            "horovod_sentry_": 4}         # explicit prefix mention: ok
    found = metrics_docs.check(fams, docs, {})
    assert codes_of(found) == ["HVL501", "HVL502"]
    assert found[1].key == "docs:horovod_sentry_checks"


def test_witness_off_spellings_disarm(monkeypatch):
    raw = threading.Lock()
    for spelling in ("0", "false", "off", "no", ""):
        monkeypatch.setenv(witness.HOROVOD_LOCK_WITNESS, spelling)
        assert witness.maybe_wrap(raw, "X") is raw, spelling


def test_run_all_rejects_unknown_checker_names():
    with pytest.raises(ValueError, match="unknown checker"):
        runner.run_all(REPO, only=["lokcs"])


def test_maybe_wrap_is_identity_when_knob_off(monkeypatch):
    monkeypatch.delenv(witness.HOROVOD_LOCK_WITNESS, raising=False)
    raw = threading.Lock()
    assert witness.maybe_wrap(raw, "X") is raw
    monkeypatch.setenv(witness.HOROVOD_LOCK_WITNESS, "1")
    wrapped = witness.maybe_wrap(raw, "X")
    assert isinstance(wrapped, witness.WitnessedLock)


def test_witness_wired_into_registry_lock(monkeypatch):
    monkeypatch.setenv(witness.HOROVOD_LOCK_WITNESS, "1")
    from horovod_tpu.obs.registry import Registry

    reg = Registry()
    assert isinstance(reg._lock, witness.WitnessedLock)
    # and the wrapped lock still behaves like one
    c = reg.counter("horovod_witness_smoke_total", "help")
    c.inc()
    assert reg.snapshot()["horovod_witness_smoke_total"]


# -- the enforcement: repo self-check + CLI contract --------------------------

def test_repo_is_clean_under_the_full_suite():
    result = runner.run_all(REPO)
    rendered = "\n".join(f.render() for f in result["findings"])
    assert result["ok"], f"hvdlint findings:\n{rendered}"
    assert set(result["checkers"]) == {
        "knobs", "locks", "collectives", "wire", "metrics_docs",
        "errors", "markers"}


def test_seeded_violations_all_fire_through_run_all(tmp_path):
    """End-to-end over a synthetic mini-repo: one violation per checker
    family lands with the right code through the real runner path."""
    (tmp_path / "tools").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "metrics.md").write_text("")
    (tmp_path / "pyproject.toml").write_text(
        '[tool.pytest.ini_options]\nmarkers = ["slow: x"]\n')
    mod_from(tmp_path, "horovod_tpu/core/config.py", FAKE_CONFIG)
    mod_from(tmp_path, "horovod_tpu/core/status.py", FAKE_STATUS)
    mod_from(tmp_path, "horovod_tpu/ops/controller.py", FAKE_CONTROLLER)
    mod_from(tmp_path, "horovod_tpu/ops/messages.py", FAKE_MESSAGES)
    mod_from(tmp_path, "horovod_tpu/bad.py", '''
        import os
        x = os.environ.get("HOROVOD_SNEAKY", "")
        def f(self):
            with self._alock:
                with self._block: pass
        def g(self):
            with self._block:
                with self._alock: pass
        def h(x):
            if rank() == 0:
                return allreduce(x)
    ''')
    mod_from(tmp_path, "tests/test_y.py", '''
        import pytest
        @pytest.mark.mystery
        def test_a():
            pass
    ''')
    result = runner.run_all(str(tmp_path))
    got = set(codes_of(result["findings"]))
    # HVL4xx: the fake controller's "teleport" tag + stale real-registry
    # entries both fire; HVL1xx literal + undocumented; HVL2xx cycle;
    # HVL3xx divergence; HVL6xx taxonomy; HVL701 marker
    for expected in ("HVL101", "HVL103", "HVL201", "HVL301", "HVL401",
                     "HVL403", "HVL601", "HVL602", "HVL701"):
        assert expected in got, (expected, sorted(got))
    assert not result["ok"]


def test_hvdlint_cli_json_contract():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "hvdlint.py"),
         "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    last = proc.stdout.strip().splitlines()[-1]
    summary = json.loads(last)
    assert summary["tool"] == "hvdlint"
    assert summary["ok"] is True
    assert summary["findings"] == 0


def test_every_code_is_documented():
    with open(os.path.join(REPO, "docs", "troubleshooting.md"),
              encoding="utf-8") as f:
        troubleshooting = f.read()
    with open(os.path.join(REPO, "docs", "analysis.md"),
              encoding="utf-8") as f:
        analysis_doc = f.read()
    for code in base.CODES:
        # analysis.md documents ranges ("HVL101–103"); accept either the
        # exact code or its range start being present
        assert code in troubleshooting, f"{code} missing a "\
            "troubleshooting row"
        prefix = code[:-1]
        assert code in analysis_doc or prefix in analysis_doc, \
            f"{code} missing from docs/analysis.md"


def test_lint_marker_is_registered_and_used_here():
    lib = base.load_tree(REPO, ["tests"])
    this = next(m for m in lib if m.rel == "tests/test_analysis.py")
    with open(os.path.join(REPO, "pyproject.toml"),
              encoding="utf-8") as f:
        registered = markers.registered_markers(f.read())
    assert "lint" in registered
    assert "lint" in markers.used_markers([this])
