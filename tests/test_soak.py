"""Randomized re-init soak — the test that found the shared-port
re-registration race (CONTROLLER_RESTARTING refusal, ops/controller.py).

Each rank loops ``init(); <30 cycles of randomized named collectives,
correctness-checked>; shutdown()`` for a fixed wall-clock budget, so the
world continuously tears down and rebuilds its controller on one port —
the reference lifecycle (``hvd.init`` after ``hvd.shutdown``) under churn.
A dying previous service serving a next-world hello used to surface as a
spurious mid-epoch SHUT_DOWN_ERROR within ~60 s of this workload."""

import os
import sys

from horovod_tpu.runner import launch

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_soak_worker.py")


def test_reinit_soak_three_ranks():
    env = dict(os.environ)
    env["SOAK_S"] = "45"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, _WORKER], np=3, host_data_plane=True,
                env_extra=env, job_timeout_s=240.0)
    assert rc == 0
