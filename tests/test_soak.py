"""Randomized re-init soak — the test that found the shared-port
re-registration race (CONTROLLER_RESTARTING refusal, ops/controller.py).

Each rank loops ``init(); <30 cycles of randomized named collectives,
correctness-checked>; shutdown()`` for a fixed wall-clock budget, so the
world continuously tears down and rebuilds its controller on one port —
the reference lifecycle (``hvd.init`` after ``hvd.shutdown``) under churn.
A dying previous service serving a next-world hello used to surface as a
spurious mid-epoch SHUT_DOWN_ERROR within ~60 s of this workload."""

import os
import sys

import pytest

from horovod_tpu import cc
from horovod_tpu.runner import launch


# Subprocess/soak-heavy by design: excluded from the quick tier (-m "not soak").
pytestmark = pytest.mark.soak

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_soak_worker.py")


@pytest.mark.parametrize("knobs", [
    {},
    # live autotuner (fusion threshold / cycle time mutation) + timeline
    # writer churn across every world lifecycle (155 lifecycles validated
    # clean at 150 s before shortening for CI). The default policy
    # backend no longer needs the native core (docs/autotune.md); this
    # variant pins the NATIVE GP backend to keep exercising the C++
    # drain loop, so it still skips where cc is not built.
    pytest.param(
        {"HOROVOD_AUTOTUNE": "1", "HOROVOD_AUTOTUNE_BACKEND": "native",
         "HOROVOD_TIMELINE": "@tmp@"},
        marks=pytest.mark.skipif(not cc.available(),
                                 reason="the native GP backend needs "
                                        "the native core")),
], ids=["plain", "autotune-timeline"])
def test_reinit_soak_three_ranks(knobs, tmp_path):
    env = dict(os.environ)
    env.update({k: (str(tmp_path / "soak_tl.json") if v == "@tmp@" else v)
                for k, v in knobs.items()})
    env["SOAK_S"] = "45"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, _WORKER], np=3, host_data_plane=True,
                env_extra=env, job_timeout_s=240.0)
    assert rc == 0


def test_device_plane_soak_three_ranks():
    """Randomized mixed numpy/jax traffic over the eager XLA data plane
    (gloo 3-process world): async dispatch, finalizer union waits, and
    launch-order compatibility between host-fed and device-resident
    ranks under sustained churn. Validated at 5 min/1.6k collectives per
    rank; runs a short budget here."""
    from horovod_tpu.runner.launcher import _free_port

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_xla_soak_worker.py")
    env = dict(os.environ)
    env.update({
        "HOROVOD_DATA_PLANE": "xla",
        "HOROVOD_TEST_JAX_COORD": f"127.0.0.1:{_free_port()}",
        "SOAK_S": "25",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    })
    rc = launch([sys.executable, worker], np=3, env_extra=env,
                job_timeout_s=240.0)
    assert rc == 0


def test_threaded_submission_soak_two_ranks():
    """Three API threads per rank submit concurrently (disjoint name
    spaces, identical sets across ranks, per-rank interleavings differ):
    the reference's async-hook reorder tolerance under churn. Count-based
    on purpose - a wall-clock budget would let a fast rank finish and
    shut down mid-submission on the slow rank, which is the documented
    SHUT_DOWN_ERROR semantics, not a soak failure."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_thread_soak_worker.py")
    env = dict(os.environ)
    env["SOAK_CYCLES"] = "80"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, worker], np=2, host_data_plane=True,
                env_extra=env, job_timeout_s=240.0)
    assert rc == 0


@pytest.mark.parametrize("controller", [
    pytest.param("native",
                 marks=pytest.mark.skipif(not cc.available(),
                                          reason="native core not built")),
    "python",
], ids=["native", "python"])
def test_subset_churn_soak_four_ranks(controller):
    """Alternating subset memberships across world lifecycles — the soak
    that found the cross-world registration race (a non-member of world N
    racing into world N+1 superseded a LIVE member's rank on the shared
    port; fixed by the world-identity protocol, WORLD_MISMATCH in
    core.status). Count-based: all launcher ranks run the same epoch
    schedule, and a non-member cannot join a member-world stop broadcast.
    Validated at 150 rounds; runs a shorter budget here."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_subset_soak_worker.py")
    env = dict(os.environ)
    env["SOAK_ROUNDS"] = "25"
    env["HOROVOD_NATIVE_CONTROLLER"] = "1" if controller == "native" else "0"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, worker], np=4, host_data_plane=True,
                env_extra=env, job_timeout_s=240.0)
    assert rc == 0


@pytest.mark.parametrize("kill_cycle", [0, 2, 9, 33])
def test_death_churn_soak_three_ranks(kill_cycle):
    """Failure injection at randomized stream positions: the victim dies
    at a different collective cycle each case (during negotiation,
    payload exchange, or idle — wherever the cycle lands), and every
    survivor must assert SHUT_DOWN_ERROR semantics within the bound.
    Reuses test_multiprocess's direct-Popen world harness: the
    launcher's die-together policy would terminate survivors before
    they can assert."""
    import test_multiprocess as mp

    size = 3
    mp._run_world(
        None, size, timeout=120.0,
        worker=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_death_soak_worker.py"),
        extra_env={
            "HOROVOD_TEST_KILL_CYCLE": str(kill_cycle),
            "HOROVOD_TEST_SEED": str(11 + kill_cycle),
            "PYTHONPATH": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        },
        expected_codes={size - 1: 7}, ok_marker="DSOAK-OK")


def test_torch_train_churn_two_ranks():
    """Sustained real training through the torch binding: per-backward
    gradient hooks, backward_passes_per_step accumulation windows, fp16
    wire compression, and the cross-rank identical-weights invariant
    checked every 10 steps (validated at 120 steps; shorter here)."""
    pytest.importorskip("torch")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_torch_soak_worker.py")
    env = dict(os.environ)
    env["SOAK_STEPS"] = "60"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, worker], np=2, host_data_plane=True,
                env_extra=env, job_timeout_s=240.0)
    assert rc == 0


def test_tf_train_churn_two_ranks():
    """Sustained DistributedGradientTape stepping through ONE traced
    tf.function graph: trace-time collective names must hold across many
    executions, with the cross-rank identical-weights invariant checked
    every 10 steps."""
    pytest.importorskip("tensorflow")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_tf_soak_worker.py")
    env = dict(os.environ)
    env["SOAK_STEPS"] = "40"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    rc = launch([sys.executable, worker], np=2, host_data_plane=True,
                env_extra=env, job_timeout_s=300.0)
    assert rc == 0
