"""Fused reduce+apply tests (docs/tensor-fusion.md §fused apply).

The apply-fused tentpole's battery: ApplyRule math vs real optax, the
bucket-vs-leaf program-family bit-exactness the whole design rests on,
fingerprint/cache-identity semantics, negotiator fusion keying, the
donation HLO audit, knob/ladder plumbing, and multi-process worlds —
fused vs two-dispatch bit-exactness for SGD/momentum/Adam, sentry
skip/zero interplay under nan@rank1 chaos, native-controller and size-1
degrades. Named ``zz`` to sort past the 870 s tier-1 truncation point
(ROADMAP operational note); the dryrun subprocess lives under ``slow``.
"""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.ops import fused_apply as fa  # noqa: E402
from horovod_tpu.ops.messages import (  # noqa: E402
    DataType,
    Request,
    RequestList,
    RequestType,
    ResponseType,
)

pytestmark = pytest.mark.fused_apply

RULES = {
    "sgd": fa.ApplyRule("sgd", 0.1),
    "momentum": fa.ApplyRule("momentum", 0.1, momentum=0.9),
    "nesterov": fa.ApplyRule("momentum", 0.1, momentum=0.9,
                             nesterov=True),
    "adam": fa.ApplyRule("adam", 1e-3),
}


# -- rule math ----------------------------------------------------------------

@pytest.mark.parametrize("kind", ["sgd", "momentum", "nesterov", "adam"])
def test_rule_math_matches_real_optax(kind):
    """The optax twins implement the textbook formulas: updates and
    state track real optax within float32 roundoff (1-ulp differences
    are expected — XLA fuses the jitted chain where optax's eager
    per-op dispatch rounds between ops; the twins' own paths are pinned
    BIT-exact below)."""
    import jax
    import jax.numpy as jnp
    import optax

    refs = {
        "sgd": optax.sgd(0.1),
        "momentum": optax.sgd(0.1, momentum=0.9),
        "nesterov": optax.sgd(0.1, momentum=0.9, nesterov=True),
        "adam": optax.adam(1e-3),
    }
    mine, ref = fa.as_optax(RULES[kind]), refs[kind]
    rng = np.random.RandomState(3)
    params = {"w": jnp.asarray(rng.randn(9).astype(np.float32)),
              "b": jnp.asarray(rng.randn(2, 3).astype(np.float32))}
    s_m, s_r = mine.init(params), ref.init(params)
    for _ in range(3):
        g = jax.tree_util.tree_map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32)), params)
        u_m, s_m = mine.update(g, s_m, params)
        u_r, s_r = ref.update(g, s_r, params)
        for k in u_m:
            np.testing.assert_allclose(np.asarray(u_m[k]),
                                       np.asarray(u_r[k]),
                                       rtol=2e-6, atol=1e-7)


@pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
def test_bucket_vs_leaf_same_program_family_bit_exact(kind):
    """THE load-bearing invariant: one leaf's slice of the fused bucket
    program equals the same program run over the leaf alone, bit for
    bit, across steps — elementwise math plus shape-independent XLA
    fusion. This is what makes fused == split == two-dispatch."""
    rule = RULES[kind]
    rng = np.random.RandomState(7)
    sizes = [5, 11, 3]
    ps = [rng.randn(n).astype(np.float32) for n in sizes]
    slots = [[np.zeros(n, np.float32) for n in sizes]
             for _ in range(rule.nslots)]
    ps_b = np.concatenate(ps)
    slots_b = [np.concatenate(s) for s in slots]
    fn = fa.bucket_apply_fn(rule, True, 2)
    offs = np.cumsum([0] + sizes)
    for step in range(1, 4):
        gs = [rng.randn(n).astype(np.float32) for n in sizes]
        out = fn(np.concatenate(gs), ps_b, np.int32(step), *slots_b)
        ps_b = np.asarray(out[0])
        slots_b = [np.asarray(s) for s in out[3:]]
        for i, g in enumerate(gs):
            res = fn(g, ps[i], np.int32(step),
                     *[s[i] for s in slots])
            ps[i] = np.asarray(res[0])
            for k in range(rule.nslots):
                slots[k][i] = np.asarray(res[3 + k])
            sl = slice(offs[i], offs[i + 1])
            assert np.array_equal(ps[i], ps_b[sl]), (kind, step, i)
            for k in range(rule.nslots):
                assert np.array_equal(slots[k][i], slots_b[k][sl])


def test_census_gate_is_the_zeroed_grad_step():
    """A non-finite batch under the census gate lands exactly the step
    a zeroed gradient would (the sentry's skip semantics): params move
    by the zero-grad update, slots decay identically, census counts
    land in the two scalars."""
    rule = RULES["momentum"]
    g = np.array([1.0, np.nan, 2.0, 3.0], np.float32)
    p = np.ones(4, np.float32)
    tr = np.full(4, 0.5, np.float32)
    gated = fa.bucket_apply_fn(rule, True, 2)(g, p, np.int32(5), tr)
    ref = fa.bucket_apply_fn(rule, True, 2)(
        np.zeros(4, np.float32), p, np.int32(5), tr)
    assert int(gated[1]) == 1 and int(gated[2]) == 0  # (nan, inf)
    assert np.array_equal(np.asarray(gated[0]), np.asarray(ref[0]))
    assert np.array_equal(np.asarray(gated[3]), np.asarray(ref[3]))


def test_fingerprint_is_the_hyperparameter_identity():
    a = fa.ApplyRule("adam", 1e-3)
    assert a.fingerprint == fa.ApplyRule("adam", 1e-3).fingerprint
    for other in (fa.ApplyRule("adam", 2e-3),
                  fa.ApplyRule("adam", 1e-3, b1=0.8),
                  fa.ApplyRule("adam", 1e-3, eps=1e-6),
                  fa.ApplyRule("adam", 1e-3, loss_scale=128.0),
                  fa.ApplyRule("sgd", 1e-3)):
        assert other.fingerprint != a.fingerprint, other
    with pytest.raises(ValueError, match="unknown fused-apply rule"):
        fa.ApplyRule("adagrad", 0.1)
    with pytest.raises(ValueError, match="loss_scale"):
        fa.ApplyRule("sgd", 0.1, loss_scale=0.0)


# -- negotiation + cache identity ---------------------------------------------

def _req(name, fp, rank=0, codec="none"):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=(8,), codec=codec, apply_fingerprint=fp)


def test_cache_identity_misses_on_hyperparam_change():
    """The response-cache request identity carries the fingerprint: an
    optimizer-hyperparameter change (new fingerprint) is a MISS, never
    a replay of a layout negotiated under a different apply program."""
    from horovod_tpu.ops.response_cache import (
        ResponseCache,
        request_identity,
    )

    fp_a = RULES["adam"].fingerprint
    fp_b = fa.ApplyRule("adam", 2e-3).fingerprint
    assert request_identity(_req("t", fp_a)) != \
        request_identity(_req("t", fp_b))
    from horovod_tpu.ops.messages import Response

    cache = ResponseCache(8)
    resp = Response(ResponseType.ALLREDUCE, tensor_names=["t"],
                    tensor_dtype=DataType.FLOAT32, fused_apply=fp_a)
    cache.insert_cycle({"t": _req("t", fp_a)}, [resp])
    assert cache.plan_cycle([_req("t", fp_a)]) is not None
    assert cache.plan_cycle([_req("t", fp_b)]) is None  # the miss


def test_negotiator_fuses_by_fingerprint_and_errors_on_mismatch():
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import Negotiator

    fp = RULES["sgd"].fingerprint
    neg = Negotiator(2, Config().fusion_threshold_bytes)
    for rank in (0, 1):
        neg.add_request_list(RequestList(rank=rank, requests=[
            _req("a", fp, rank), _req("b", fp, rank),
            _req("c", "", rank)]))
    out = neg.construct_response_list()
    kinds = [(r.response_type, tuple(r.tensor_names), r.fused_apply)
             for r in out.responses]
    # same-fingerprint tensors fuse into ONE apply-capable batch; the
    # plain allreduce never joins it
    assert (ResponseType.ALLREDUCE, ("a", "b"), fp) in kinds, kinds
    assert (ResponseType.ALLREDUCE, ("c",), "") in kinds, kinds
    # cross-rank rule mismatch is a coordinator error, like the codec
    neg = Negotiator(2, Config().fusion_threshold_bytes)
    neg.add_request_list(RequestList(rank=0, requests=[_req("t", fp, 0)]))
    neg.add_request_list(RequestList(rank=1, requests=[
        _req("t", RULES["adam"].fingerprint, 1)]))
    out = neg.construct_response_list()
    assert out.responses[0].response_type == ResponseType.ERROR
    assert "fused-apply" in out.responses[0].error_message


# -- donation HLO audit -------------------------------------------------------

def test_reduce_apply_hlo_single_program_with_donated_buckets():
    """The single-dispatch claim, audited: ONE compiled module whose
    ``input_output_alias`` header covers the grad bucket (aliasing the
    raw reduced output) AND the param/slot buckets — f32 and the int8
    codec variant alike (the ``reduce_donation_hlo`` precedent)."""
    from horovod_tpu.ops.xla_plane import XlaDataPlane

    plane = XlaDataPlane(types.SimpleNamespace(rank=0, size=1))
    for codec in ("none", "int8"):
        for rule in (RULES["sgd"], RULES["adam"]):
            hlo = plane.reduce_apply_hlo(5000, rule, codec=codec,
                                         gate=True, denom=2)
            assert "input_output_alias" in hlo, (codec, rule.kind)
            line = [l for l in hlo.splitlines()
                    if "input_output_alias" in l][0]
            n_alias = line.count("alias)")
            assert n_alias >= 2 + rule.nslots, (codec, rule.kind, line)


def test_spmd_reduce_apply_companion():
    """The in-jit companion (groundwork for the ZeRO sharded update):
    ``spmd.reduce_apply`` fuses psum + the shared ApplyRule math into
    one traced expression, matching the bucket program applied to the
    mean gradient."""
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.spmd import reduce_apply
    from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh

    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    if n_dev < 2:
        pytest.skip("needs a multi-device mesh (conftest XLA_FLAGS)")
    rule = RULES["adam"]

    def step(g, p, mu, nu):
        new_p, (nmu, nnu) = reduce_apply(
            g, p, (mu, nu), rule, 1, DATA_AXIS, average=True)
        return new_p, nmu, nnu

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(DATA_AXIS), P(), P(), P()),
        out_specs=(P(), P(), P()), check_vma=False))
    rng = np.random.RandomState(11)
    g = rng.randn(n_dev, 6).astype(np.float32)
    p = rng.randn(6).astype(np.float32)
    z = np.zeros(6, np.float32)
    new_p, mu, nu = f(g, p, z, z)
    ref = fa.bucket_apply_fn(rule, False, 1)(
        (g.sum(axis=0) / n_dev).astype(np.float32), p, np.int32(1), z, z)
    np.testing.assert_allclose(
        np.asarray(new_p).reshape(-1), np.asarray(ref[0]),
        rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(mu).reshape(-1), np.asarray(ref[3]),
        rtol=1e-6, atol=1e-7)


# -- submission validation ----------------------------------------------------

def test_fused_apply_async_validation():
    from horovod_tpu import ops

    g = np.ones(4, np.float32)
    with pytest.raises(TypeError, match="ApplyRule"):
        ops.fused_apply_async(g, g, (), object(), 1)
    with pytest.raises(TypeError, match="float32"):
        ops.fused_apply_async(g.astype(np.float64), g, (),
                              RULES["sgd"], 1)
    with pytest.raises(ValueError, match="slot"):
        ops.fused_apply_async(g, g, (), RULES["adam"], 1)


# -- knob / ladder / decision log ---------------------------------------------

def test_policy_fused_apply_knob_gating_and_decision_log():
    """The ``fused_apply`` ladder entry (docs/autotune.md): present only
    when the operator armed the plane (HOROVOD_FUSED_APPLY=1), never
    pinned by that env (numerics-exact strategy choice belongs to the
    tuner), and its moves land in the JSONL decision log."""
    import json

    from horovod_tpu.core.config import Config
    from horovod_tpu.tune.policy import (
        Knob,
        TuningPolicy,
        default_knobs,
    )

    names = {k.name for k in default_knobs(Config(), extended=True)}
    assert "fused_apply" not in names  # plane not armed
    by_name = {k.name: k for k in default_knobs(
        Config(fused_apply=True), extended=True)}
    assert "fused_apply" in by_name
    knob = by_name["fused_apply"]
    assert knob.values == (0, 1) and not knob.pinned
    assert knob.current == 1
    # native wire: classic pair only, the knob never rides it
    names = {k.name for k in default_knobs(Config(fused_apply=True),
                                           extended=False)}
    assert names == {"fusion_threshold_bytes", "cycle_time_ms"}
    # decision log: drive a policy over just this knob until it moves
    records = []
    policy = TuningPolicy([Knob("fused_apply", (0, 1), 1)],
                          window=1, cooldown=0,
                          decision_sink=records.append)
    for _ in range(6):
        policy.observe(1e6, 1e3)
    moved = [r for r in records if r["action"] != "init"]
    assert moved and any(r["knob"] == "fused_apply" for r in moved)
    for record in records:
        json.dumps(record)  # the JSONL contract
        assert "fused_apply" in record["config"]


def test_size1_fused_apply_and_tuned_knob_flip(monkeypatch):
    """Size-1 world: apply-capable batches land applied parameters
    bit-exact to the shared program run locally, and the tuned
    ``fused_apply`` knob flips the engine's execution strategy (split
    still lands applied parameters)."""
    monkeypatch.setenv("HOROVOD_FUSED_APPLY", "1")
    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    try:
        rule = RULES["adam"]
        tx = hvd.DistributedOptimizer(fa.as_optax(rule))
        params = {"w": np.arange(16, dtype=np.float32)}
        state = tx.init(params)
        grads = {"w": np.full(16, 0.25, np.float32)}
        p1, s1 = hvd.apply_step(tx, grads, state, params)
        eng = get_engine()
        assert eng.apply_stats()["fused_batches"] == 1
        ref = fa.bucket_apply_fn(rule, False, 1)(
            grads["w"], params["w"], np.int32(1),
            np.zeros(16, np.float32), np.zeros(16, np.float32))
        np.testing.assert_array_equal(np.asarray(p1["w"]),
                                      np.asarray(ref[0]))
        # the tuning plane's piggyback flips the strategy live
        msg = types.SimpleNamespace(tuned_knobs={"fused_apply": 0})
        eng._apply_tuned_knobs(msg)
        assert not eng._fused_apply_exec
        p2, s2 = hvd.apply_step(tx, grads, s1, p1)
        stats = eng.apply_stats()
        assert stats["split_batches"] == 1, stats
        ref2 = fa.bucket_apply_fn(rule, False, 1)(
            grads["w"], np.asarray(p1["w"]), np.int32(2),
            np.asarray(s1.inner.slots[0]["w"]),
            np.asarray(s1.inner.slots[1]["w"]))
        np.testing.assert_array_equal(np.asarray(p2["w"]),
                                      np.asarray(ref2[0]))
    finally:
        hvd.shutdown()


def test_peer_verdict_rewrites_locally_clean_fused_batch(monkeypatch):
    """The collective-sentry contract under fused apply: when the
    verdict exchange ORs in a PEER's bad bit while this rank's
    in-program census was clean (a peer-divergent reduced buffer — the
    sentry's "peer" kind), the already-landed full update must be
    replaced by the zero-gradient step the gated rank computed, so the
    world converges instead of silently diverging."""
    monkeypatch.setenv("HOROVOD_FUSED_APPLY", "1")
    monkeypatch.setenv("HOROVOD_GRAD_SENTRY", "skip")
    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    try:
        rule = RULES["momentum"]
        tx = hvd.DistributedOptimizer(fa.as_optax(rule))
        params = {"w": np.arange(16, dtype=np.float32)}
        state = tx.init(params)
        # seed a nonzero trace so the zero-grad step still MOVES params
        # (u = -lr * momentum * trace) — unchanged-params alone could
        # not tell the rewrite from a dropped apply
        g0 = {"w": np.full(16, 2.0, np.float32)}
        params, state = hvd.apply_step(tx, g0, state, params)
        eng = get_engine()
        # a peer saw the batch bad: every exchanged bit comes back set
        eng._sentry._exchange = lambda ordinal, bits: b"\xff"
        p_before = np.asarray(params["w"]).copy()
        tr_before = np.asarray(state.inner.slots[0]["w"]).copy()
        g1 = {"w": np.full(16, 5.0, np.float32)}  # locally clean
        params2, state2 = hvd.apply_step(tx, g1, state, params)
        trips = eng._sentry.trips
        assert trips and trips[-1][2] == "peer", trips
        # the landed state is the ZERO-grad step, not g1's update
        ref = fa.bucket_apply_fn(rule, True, 1)(
            np.zeros(16, np.float32), p_before,
            np.int32(int(state.inner.count) + 1), tr_before)
        np.testing.assert_array_equal(np.asarray(params2["w"]),
                                      np.asarray(ref[0]))
        np.testing.assert_array_equal(
            np.asarray(state2.inner.slots[0]["w"]), np.asarray(ref[3]))
    finally:
        hvd.shutdown()


# -- multi-process worlds -----------------------------------------------------

def _world_fn(opts, steps, n_leaves):
    """Per-rank body: run each optimizer kind for ``steps`` fused (or
    two-dispatch, per HOROVOD_FUSED_APPLY) apply_steps; report final
    params/slots plus engine apply/overlap/sentry stats."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops import fused_apply as fa
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    out = {"rank": rank}
    makers = {"sgd": lambda: fa.sgd(0.1),
              "momentum": lambda: fa.momentum(0.1, 0.9),
              "adam": lambda: fa.adam(1e-2)}
    for kind in opts:
        tx = hvd.DistributedOptimizer(makers[kind]())
        params = {f"l{i}": (np.arange(8 + i, dtype=np.float32) / 7 - 0.4)
                  for i in range(n_leaves)}
        state = tx.init(params)
        for step in range(steps):
            grads = {f"l{i}": np.full(8 + i,
                                      float((rank + 1) * (i + 1)
                                            * (step + 1)) / 8,
                                      np.float32)
                     for i in range(n_leaves)}
            params, state = hvd.apply_step(tx, grads, state, params)
        out[kind] = {
            "params": {k: np.asarray(v).tolist()
                       for k, v in params.items()},
            "slots": [{k: np.asarray(v).tolist() for k, v in s.items()}
                      for s in state.inner.slots],
            "count": int(state.inner.count),
        }
    eng = get_engine()
    out["apply"] = eng.apply_stats()
    out["overlap"] = eng.overlap_stats()
    integrity = eng.integrity_stats()
    out["sentry"] = integrity["sentry"]
    hvd.shutdown()
    return out


def _run_world(np_, opts=("sgd",), steps=4, n_leaves=3, **env):
    from horovod_tpu.runner import run

    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0", **env}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        return run(_world_fn, args=(tuple(opts), steps, n_leaves),
                   np=np_, timeout_s=180.0, start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _assert_states_equal(a, b, kinds):
    for kind in kinds:
        assert a[kind]["params"] == b[kind]["params"], kind
        assert a[kind]["slots"] == b[kind]["slots"], kind
        assert a[kind]["count"] == b[kind]["count"], kind


def test_mp_fused_bit_exact_vs_two_dispatch_all_rules():
    """The acceptance pin: fused apply is BIT-exact against the
    two-dispatch path for SGD, momentum, and Adam in a real 2-proc
    world, with the fused route actually exercised (apply batches > 0,
    one apply dispatch per batch) and the two-dispatch world landing
    zero apply-capable batches."""
    kinds = ("sgd", "momentum", "adam")
    fused = _run_world(2, opts=kinds, HOROVOD_FUSED_APPLY="1")
    plain = _run_world(2, opts=kinds, HOROVOD_FUSED_APPLY="0")
    fr = {r["rank"]: r for r in fused}
    pr = {r["rank"]: r for r in plain}
    _assert_states_equal(fr[0], fr[1], kinds)  # ranks identical
    _assert_states_equal(fr[0], pr[0], kinds)  # fused == two-dispatch
    for r in fused:
        st = r["apply"]
        assert st["fused_batches"] > 0, st
        assert st["split_batches"] == 0, st
        assert st["apply_dispatches"] == st["fused_batches"], st
    for r in plain:
        assert r["apply"]["fused_batches"] == 0, r["apply"]
        assert r["apply"]["apply_dispatches"] == 0, r["apply"]


def test_mp_fused_bit_exact_on_native_negotiation_core():
    """The native C++ negotiation core's schema predates the
    fingerprint: the NativeNegotiator wrapper's Python bookkeeping
    stamps and splits batches, so fused apply stays available and
    bit-exact there (the PR 1 codec pattern)."""
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native core unavailable: {cc.load_error()}")
    fused = _run_world(2, opts=("adam",), HOROVOD_FUSED_APPLY="1",
                       HOROVOD_NATIVE_CORE="1")
    plain = _run_world(2, opts=("adam",), HOROVOD_FUSED_APPLY="0",
                       HOROVOD_NATIVE_CORE="1")
    fr = {r["rank"]: r for r in fused}
    pr = {r["rank"]: r for r in plain}
    _assert_states_equal(fr[0], fr[1], ("adam",))
    _assert_states_equal(fr[0], pr[0], ("adam",))
    for r in fused:
        assert r["apply"]["fused_batches"] > 0, r["apply"]


@pytest.mark.parametrize("policy", ["skip", "zero"])
def test_mp_sentry_gate_under_nan_chaos(policy):
    """Sentry interplay under ``nan@rank1`` data chaos: the in-program
    census gate makes the poisoned batch a collective no-op — both
    ranks trip at the same ordinal with identical final state, and the
    fused world stays BIT-exact to the two-dispatch world under the
    same fault (single-leaf steps pin batch == step, so the injection
    ordinal is deterministic)."""
    env = {"HOROVOD_GRAD_SENTRY": policy,
           "HOROVOD_CHAOS": "nan@rank1:msg2,seed:5"}
    fused = _run_world(2, opts=("momentum",), n_leaves=1, steps=4,
                       HOROVOD_FUSED_APPLY="1", **env)
    plain = _run_world(2, opts=("momentum",), n_leaves=1, steps=4,
                       HOROVOD_FUSED_APPLY="0", **env)
    fr = {r["rank"]: r for r in fused}
    pr = {r["rank"]: r for r in plain}
    _assert_states_equal(fr[0], fr[1], ("momentum",))
    _assert_states_equal(fr[0], pr[0], ("momentum",))
    for r in fused:
        sentry = r["sentry"]
        assert sentry["collective"], sentry  # the real-wire OR-fold ran
        trips = sentry["trips"]
        assert len(trips) == 1 and trips[0][2] == "nan", sentry
    # identical trip ordinal on both ranks (the collective verdict)
    assert fr[0]["sentry"]["trips"] == fr[1]["sentry"]["trips"]
    # clean world sanity: no trips, different final state than poisoned
    clean = _run_world(2, opts=("momentum",), n_leaves=1, steps=4,
                       HOROVOD_FUSED_APPLY="1",
                       HOROVOD_GRAD_SENTRY=policy)
    cr = {r["rank"]: r for r in clean}
    assert cr[0]["sentry"]["trips"] == []
    assert cr[0]["momentum"]["params"] != fr[0]["momentum"]["params"]


def test_mp_native_controller_degrades_to_split():
    """The native controller's binary wire predates the fingerprint
    field: apply-capable submissions degrade deterministically to the
    split reduce-then-apply execution (warned once) — applied
    parameters still land, bit-exact to the two-dispatch world."""
    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native controller unavailable: {cc.load_error()}")
    fused = _run_world(2, opts=("sgd",), HOROVOD_FUSED_APPLY="1",
                       HOROVOD_NATIVE_CONTROLLER="1")
    plain = _run_world(2, opts=("sgd",), HOROVOD_FUSED_APPLY="0",
                       HOROVOD_NATIVE_CONTROLLER="1")
    fr = {r["rank"]: r for r in fused}
    pr = {r["rank"]: r for r in plain}
    _assert_states_equal(fr[0], pr[0], ("sgd",))
    for r in fused:
        st = r["apply"]
        assert st["fused_batches"] == 0, st  # the degrade landed
        assert st["split_batches"] > 0, st
        assert st["apply_dispatches"] > 0, st


def test_mp_fused_apply_under_subbuffer_overlap():
    """The headline composition: subbuffers=2 + fused apply — the
    overlap pipeline runs (the update math now rides inside the
    overlapped flush), bit-exact vs the single-flush fused world."""
    base = {"HOROVOD_FUSED_APPLY": "1"}
    piped = _run_world(2, opts=("adam",), n_leaves=6, steps=5,
                       HOROVOD_FUSION_SUBBUFFERS="2", **base)
    single = _run_world(2, opts=("adam",), n_leaves=6, steps=5,
                        HOROVOD_FUSION_SUBBUFFERS="1", **base)
    fr = {r["rank"]: r for r in piped}
    sr = {r["rank"]: r for r in single}
    _assert_states_equal(fr[0], fr[1], ("adam",))
    _assert_states_equal(fr[0], sr[0], ("adam",))
    for r in piped:
        assert r["overlap"]["pipelined"], r["overlap"]
        assert r["apply"]["fused_batches"] > 0, r["apply"]
    for r in single:
        assert not r["overlap"]["pipelined"], r["overlap"]


@pytest.mark.slow
def test_dryrun_fused_apply_certification():
    """The driver-facing certification end to end, as __main__ runs it."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_fused_apply(); "
         "print('dryrun_fused_apply OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=580)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "dryrun_fused_apply OK" in result.stdout, result.stdout
