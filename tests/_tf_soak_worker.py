"""TF2 front-end churn: sustained DistributedGradientTape stepping.

Targets the TF binding's stateful machinery — trace-time op names inside
``tf.function`` (one graph, many executions), the custom-gradient
collective rules, and the batched py_function grad path — with the
cross-rank identical-weights invariant checked periodically."""
import os
import sys

os.environ.pop("JAX_PLATFORMS", None)
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import horovod_tpu as hvd

STEPS = int(os.environ.get("SOAK_STEPS", "60"))
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
import tensorflow as tf

import horovod_tpu.tensorflow as hvd_tf

hvd.init()
tf.random.set_seed(4242)
model = tf.keras.Sequential([
    tf.keras.Input(shape=(6,)),
    tf.keras.layers.Dense(8, activation="relu"),
    tf.keras.layers.Dense(2),
])
opt = tf.keras.optimizers.SGD(0.05)
hvd_tf.broadcast_variables(model.variables, root_rank=0)

g = tf.random.Generator.from_seed(99)  # same stream on every rank


@tf.function  # ONE traced graph executed STEPS times: the trace-time
def step(x, y):  # name assignment must hold across executions
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean(tf.square(model(x, training=True) - y))
    tape = hvd_tf.DistributedGradientTape(tape)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))
    return loss


for step_no in range(STEPS):
    x = g.normal((4, 6)) + rank * 0.1
    y = g.normal((4, 2))
    step(x, y)
    if step_no % 10 == 0:
        flat = np.concatenate([v.numpy().ravel()
                               for v in model.trainable_variables])
        gathered = hvd_tf.allgather(
            tf.constant(flat[None, :]), name=f"tfw.eq.{step_no}").numpy()
        for r in range(size):
            np.testing.assert_allclose(
                gathered[r], flat, rtol=1e-4,
                err_msg=f"rank weights diverged at step {step_no}")

hvd.shutdown()
print(f"TFSOAK-OK rank {rank} steps={STEPS}", flush=True)
os._exit(0)
