"""The driver artifacts must stay runnable: ``entry()`` (single-chip
compile check) and ``dryrun_multichip`` (virtual-mesh sharding check) gate
external credit for the build, so their contracts are pinned here."""

import numpy as np
import pytest

# Full-model compiles in subprocesses (~3 min): excluded from the quick
# tier (-m "not soak").
pytestmark = pytest.mark.soak


def test_entry_compiles_and_runs():
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 1000)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_subprocess():
    """The multi-chip gate artifact, exactly as the driver invokes it
    (own process: dryrun pins its own platform/device-count globals)."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "OK" in result.stdout
    # The default dryrun certifies BOTH collective routes (round-4 verdict
    # next #2): the driver artifact's tail must show the flat step, the
    # forced-hierarchical step, and the factored HLO evidence.
    assert "DP step OK (hierarchical allreduce: off (flat psum))" \
        in result.stderr
    assert "DP step OK (hierarchical allreduce: ON)" in result.stderr
    assert "factored-step HLO" in result.stderr


import pytest


@pytest.mark.slow
def test_dryrun_elastic_restart_subprocess():
    """The elastic-restart certification, exactly as the driver invokes
    it. Slow-tier: the same kill→relaunch→restore machinery is pinned in
    tier-1 by test_elastic.py's acceptance test."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_elastic_restart(); "
         "print('OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "OK" in result.stdout
    assert "elastic restart OK" in result.stderr


@pytest.mark.slow
def test_dryrun_chaos_subprocess():
    """The chaos certification, exactly as the driver invokes it.
    Slow-tier: the same drop→reconnect→dedup machinery is pinned in
    tier-1 by test_chaos.py's acceptance matrix."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_chaos(); print('OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    assert "OK" in result.stdout
    assert "chaos OK" in result.stderr


@pytest.mark.slow
@pytest.mark.integrity
def test_dryrun_integrity_subprocess():
    """The data-plane integrity certification, exactly as the driver
    invokes it. Slow-tier: the same sentry/consensus machinery is pinned
    by tests/test_wire_integrity.py's acceptance cells."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_integrity(); print('OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=420)
    assert result.returncode == 0, result.stderr
    assert "OK" in result.stdout
    assert "integrity OK" in result.stderr


def test_init_on_host_cpu_noop_on_cpu():
    """On a CPU default backend the helper defers to plain on-device init
    (None) — there is no separate host backend to shelter compiles on."""
    from horovod_tpu.core.platform import init_on_host_cpu

    assert init_on_host_cpu(lambda: 1, None) is None


def test_dryrun_multichip_hierarchical_16():
    """The hierarchical dryrun twin (round-3 verdict next #5): at 16
    virtual devices with HOROVOD_HIERARCHICAL_ALLREDUCE=1 the full DP
    step must compile and execute through the factored two-level route
    (the HLO shape itself is pinned in test_spmd)."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    result = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16); print('OK')"],
        cwd=root, env=env, capture_output=True, text=True, timeout=500)
    assert result.returncode == 0, result.stderr
    assert "OK" in result.stdout
    assert "hierarchical allreduce: ON" in result.stderr
