"""Elastic fault-tolerance plane: heartbeats, abort-instead-of-hang,
relaunch with state restore (docs/elastic.md).

The reference (Horovod 0.16) answers a dead worker with an infinite hang;
upstream Horovod's next subsystem era was elastic mode. These tests pin
the rebuilt contract: the deterministic kill-one-worker recovery and the
stall-deadline abort run in the tier-1 subset; the multi-restart soaks are
marked ``slow``.
"""

import os
import sys
import time

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_mp_worker.py")


# -- unit: structured abort parsing ------------------------------------------

def test_parse_aborted_ranks_forms():
    from horovod_tpu.core.status import (
        format_aborted_ranks,
        parse_aborted_ranks,
    )

    assert parse_aborted_ranks(format_aborted_ranks([3, 1, 3])) == [1, 3]
    assert parse_aborted_ranks("rank 7 exited mid-job. blah") == [7]
    assert parse_aborted_ranks(
        "Stalled ops: t [missing ranks: 0, 2] [ready ranks: 1]") == [0, 2]
    assert parse_aborted_ranks("nothing attributable here") is None
    # strict mode (for LOG text like stderr tails): only the explicit tag
    # counts — routine stall warnings and incidental phrasing are noise
    assert parse_aborted_ranks("x [aborted ranks: 4]", strict=True) == [4]
    assert parse_aborted_ranks("rank 7 exited mid-job.",
                               strict=True) is None
    assert parse_aborted_ranks(
        "Stalled ops: t [missing ranks: 0, 2] [ready ranks: 1]",
        strict=True) is None


def test_ranks_aborted_error_from_status():
    from horovod_tpu.core.status import (
        HorovodInternalError,
        RanksAbortedError,
        Status,
    )

    status = Status.unknown_error(
        "x stalled. shut down [aborted ranks: 2]")
    with pytest.raises(RanksAbortedError) as excinfo:
        status.raise_if_error()
    assert excinfo.value.ranks == [2]
    assert isinstance(excinfo.value, HorovodInternalError)
    # unattributed shutdowns keep the plain error class
    with pytest.raises(HorovodInternalError) as excinfo:
        Status.unknown_error("shut down, no details").raise_if_error()
    assert not isinstance(excinfo.value, RanksAbortedError)


def test_stall_escalation_tracker():
    from horovod_tpu.ops.controller import StallEscalation

    warning = ("... Stalled ops: grad.3 [missing ranks: 1, 2] "
               "[ready ranks: 0]")
    esc = StallEscalation(deadline_s=0.2)
    assert esc.check([warning]) is None  # first sighting starts the clock
    time.sleep(0.25)
    result = esc.check([warning])
    assert result is not None
    names, missing, reason = result
    assert names == ["grad.3"] and missing == [1, 2]
    assert "HOROVOD_STALL_SHUTDOWN_TIME_S" in reason
    assert "[aborted ranks: 1, 2]" in reason
    # a resolved stall (no longer warned about) must stop aging
    esc2 = StallEscalation(deadline_s=0.2)
    assert esc2.check([warning]) is None
    assert esc2.check(["... Stalled ops: other [missing ranks: 1] "
                       "[ready ranks: 0]"]) is None
    time.sleep(0.25)
    assert esc2.check([warning]) is None  # clock restarted
    # disabled tracker never escalates
    assert StallEscalation(0.0).check([warning]) is None
    # an authoritative all-clear (the coordinator's check ran and found
    # nothing) retires the episode immediately — no cadence wait
    esc25 = StallEscalation(deadline_s=0.2, warning_interval_s=60.0)
    assert esc25.check([warning]) is None
    assert esc25.check([], check_ran=True) is None
    time.sleep(0.25)
    assert esc25.check([warning]) is None  # fresh episode, clock restarted
    # a recovered stall followed by EMPTY batches (nothing else stalled,
    # so no non-empty snapshot ever prunes it) must not leak its clock
    # into the name's next stall episode: after the warning cadence says
    # the episode ended, a fresh warning restarts the deadline
    esc3 = StallEscalation(deadline_s=0.2, warning_interval_s=0.05)
    assert esc3.check([warning]) is None
    time.sleep(0.3)  # > 2.5x interval with no re-warning: episode over
    assert esc3.check([warning]) is None  # new episode, clock restarted
    # a CONTINUOUSLY warned stall keeps its original clock and expires
    esc4 = StallEscalation(deadline_s=0.3, warning_interval_s=0.05)
    deadline = time.monotonic() + 5.0
    fired = None
    while fired is None and time.monotonic() < deadline:
        fired = esc4.check([warning])
        time.sleep(0.05)
    assert fired is not None and fired[0] == ["grad.3"]


def test_fault_spec_parse():
    from horovod_tpu.elastic.state import parse_fault_spec

    assert parse_fault_spec("2:5") == (2, 5, 0)
    assert parse_fault_spec("0:3:1") == (0, 3, 1)
    assert parse_fault_spec("") is None
    assert parse_fault_spec("nope") is None
    assert parse_fault_spec("1:2:3:4") is None


def test_format_aborted_ranks_dedupes_and_sorts():
    from horovod_tpu.core.status import format_aborted_ranks

    assert format_aborted_ranks([5, 1, 5, 3]) == "[aborted ranks: 1, 3, 5]"
    assert format_aborted_ranks({0}) == "[aborted ranks: 0]"


def test_parse_aborted_ranks_prefers_explicit_tag():
    from horovod_tpu.core.status import parse_aborted_ranks

    # explicit tag wins over incidental rank mentions elsewhere
    msg = "rank 0 saw trouble [aborted ranks: 3] rank 9 exited mid-job"
    assert parse_aborted_ranks(msg) == [3]
    # survives the engine loop's SHUT_DOWN_ERROR rewrap
    wrapped = ("Horovod has been shut down. (cause: collective aborted "
               "[aborted ranks: 1, 2])")
    assert parse_aborted_ranks(wrapped) == [1, 2]


def test_stall_escalation_ignores_unparseable_warnings():
    from horovod_tpu.ops.controller import StallEscalation

    esc = StallEscalation(deadline_s=0.01)
    assert esc.check(["free-form warning with no stalled ops"]) is None
    assert esc.check([]) is None


def test_world_epoch_reads_env(monkeypatch):
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.core import config as _config

    monkeypatch.delenv(_config.HOROVOD_ELASTIC_EPOCH, raising=False)
    assert world_epoch() == 0
    monkeypatch.setenv(_config.HOROVOD_ELASTIC_EPOCH, "4")
    assert world_epoch() == 4


def test_worker_failed_error_names_all_ranks():
    from horovod_tpu.runner.run_api import WorkerFailedError

    err = WorkerFailedError([(1, "boom"), (3, "bang")])
    assert err.ranks == [1, 3]
    assert "rank 1" in str(err) and "boom" in str(err)
    assert "[3]" in str(err)


def test_launch_error_message_with_and_without_tail():
    from horovod_tpu.runner.launcher import LaunchError

    plain = LaunchError(2, 9)
    assert "rank 2" in str(plain) and "code 9" in str(plain)
    assert plain.stderr_tail == ""
    tailed = LaunchError(0, 1, stderr_tail="Traceback: kaput\n")
    assert "kaput" in str(tailed) and tailed.stderr_tail


def test_driver_failed_rank_attribution():
    from horovod_tpu.elastic.driver import WorkerDeadError, _failed_ranks
    from horovod_tpu.runner.launcher import LaunchError
    from horovod_tpu.runner.run_api import WorkerFailedError

    # plain exit: blame the exiting rank
    assert _failed_ranks(LaunchError(2, 13)) == [2]
    # a healthy victim's stderr names the real culprit: prefer it
    victim = LaunchError(0, 1, stderr_tail="RanksAbortedError: stalled "
                                           "[aborted ranks: 3]")
    assert _failed_ranks(victim) == [3]
    # ...but a ROUTINE stall warning in the coordinator's stderr (a
    # transient, already-recovered stall) must NOT redirect the blame
    noisy = LaunchError(0, 1, stderr_tail=(
        "[WARNING] ... Stalled ops: g [missing ranks: 3] [ready ranks: "
        "0]\nTraceback: unrelated crash"))
    assert _failed_ranks(noisy) == [0]
    assert _failed_ranks(WorkerDeadError([1, 2], 1.0, 5)) == [1, 2]
    from horovod_tpu.runner.run_api import WorkerLostError

    assert _failed_ranks(WorkerLostError([2], [0])) == [2]
    # arbitrary runtime errors are not retried, hence not attributed
    assert _failed_ranks(RuntimeError("internal bug")) == []
    # worker exceptions: abort-tagged detail wins over the reporter list
    wf = WorkerFailedError([(0, "shut down [aborted ranks: 2]")])
    assert _failed_ranks(wf) == [2]
    assert _failed_ranks(WorkerFailedError([(1, "user bug")])) == [1]
    assert _failed_ranks(TimeoutError("nothing attributable")) == []


# -- unit: health plane -------------------------------------------------------

def test_elastic_service_heartbeats_and_death():
    from horovod_tpu.elastic.health import ElasticService, HeartbeatReporter

    secret = os.urandom(32)
    service = ElasticService(secret, heartbeat_interval_s=0.05,
                             miss_limit=3)
    try:
        service.begin_epoch(0)
        reporter = HeartbeatReporter(("127.0.0.1", service.port), rank=1,
                                     epoch=0, secret=secret,
                                     interval_s=0.05)
        deadline = time.monotonic() + 5.0
        while not service._last_beat and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in service._last_beat, "no heartbeat arrived"
        assert service.dead_ranks() == []
        # a clean stop sends goodbye: never flagged dead
        reporter.stop()
        time.sleep(0.4)
        assert service.dead_ranks() == []
        # an abrupt stop (no goodbye) IS flagged dead after the miss limit
        service.begin_epoch(1)
        reporter2 = HeartbeatReporter(("127.0.0.1", service.port), rank=2,
                                      epoch=1, secret=secret,
                                      interval_s=0.05)
        deadline = time.monotonic() + 5.0
        while not service._last_beat and time.monotonic() < deadline:
            time.sleep(0.02)
        reporter2._stop.set()  # kill the loop without the goodbye path
        reporter2._thread.join(timeout=5.0)
        # undo the goodbye the stopped loop may still have sent: simulate
        # the hard-death case by re-beating then silencing
        service.begin_epoch(2)
        service._handle(("beat", 2, 2), None)
        time.sleep(0.3)
        assert service.dead_ranks() == [2]
    finally:
        service.shutdown()


def test_elastic_service_epoch_fencing_and_store():
    from horovod_tpu.elastic.health import ElasticService

    service = ElasticService(os.urandom(32), heartbeat_interval_s=0.05,
                             miss_limit=2)
    try:
        service.begin_epoch(3)
        # a straggler beat from a previous epoch must be ignored
        service._handle(("beat", 2, 0), None)
        assert service._last_beat == {}
        service._handle(("beat", 3, 0), None)
        assert 0 in service._last_beat
        # commit store: latest payload wins; fetch round-trips
        assert service._handle(("fetch",), None) == ("commit", None, None)
        service._handle(("commit", 3, {"commit_no": 1}, b"one"), None)
        service._handle(("commit", 3, {"commit_no": 2}, b"two"), None)
        kind, meta, payload = service._handle(("fetch",), None)
        assert (kind, payload) == ("commit", b"two")
        assert meta["commit_no"] == 2 and meta["epoch"] == 3
    finally:
        service.shutdown()


# -- unit: state commit/restore (single-process world) ------------------------

def test_state_commit_restore_roundtrip(hvd):
    from horovod_tpu.elastic import State

    state = State(w=np.zeros(3, np.float32), step=0,
                  extra={"lr": 0.5})
    state.w = state.w + 1.0
    state.step = 4
    state.commit()
    state.w = state.w + 99.0
    state.step = 9
    state.extra = {"lr": 0.1}
    state.restore()
    assert state.step == 4
    np.testing.assert_array_equal(state.w, 1.0)
    assert state.extra == {"lr": 0.5}
    # sync in a world of one is the identity (and re-commits)
    out = state.run(lambda s: (s.step, float(s.w[0])))
    assert out == (4, 1.0)


def test_state_rejects_reserved_names(hvd):
    from horovod_tpu.elastic import State

    with pytest.raises(ValueError):
        State()
    with pytest.raises(ValueError):
        State(commit=1)
    with pytest.raises(ValueError):
        State(_hidden=2)


# -- tier-1 acceptance: kill a worker mid-step, relaunch, restore -------------

_TOTAL_STEPS = 5


def _elastic_train_fn(total_steps):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.elastic import State

    hvd.init()
    state = State(w=np.zeros(2, np.float32), step=0)

    def train(state):
        while state.step < total_steps:
            try:
                grad = hvd.allreduce(
                    np.full(2, float(state.step + 1), np.float32),
                    average=False, name=f"el.grad.{state.step}")
            except hvd.RanksAbortedError as exc:
                # the acceptance contract: a healthy rank must see the
                # STRUCTURED abort naming the dead rank — never a hang,
                # never an anonymous shutdown
                assert 2 in exc.ranks, exc.ranks
                raise
            state.w = state.w + np.asarray(grad)
            state.step += 1
            state.commit()
        return {"rank": hvd.rank(), "size": hvd.size(),
                "epoch": world_epoch(), "step": state.step,
                "w0": float(state.w[0])}

    out = state.run(train)
    hvd.shutdown()
    return out


def test_run_elastic_kill_mid_step_restores_and_finishes():
    """THE elastic contract: a 4-rank job whose rank 2 is killed
    mid-step (fault hook fires before its 3rd commit persists) aborts
    cleanly — no hang — relaunches, restores from the last commit
    (step 2), and finishes with the correct final step count and a loss
    trajectory identical to an unfailed run."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _elastic_train_fn, args=(_TOTAL_STEPS,), np=4, min_np=2,
        max_restarts=2, backoff_s=0.2, timeout_s=180.0,
        start_timeout_s=120.0,
        heartbeat_interval_s=0.5, heartbeat_miss_limit=6,
        env_extra={"HOROVOD_ELASTIC_FAULT": "2:3",
                   "HOROVOD_CYCLE_TIME": "2"})
    assert len(results) == 4
    # w accumulates sum_k size*k over steps 1..total — bit-exact resume
    expected_w = 4.0 * sum(range(1, _TOTAL_STEPS + 1))
    for result in results:
        assert result["step"] == _TOTAL_STEPS, result
        assert result["w0"] == expected_w, (result, expected_w)
        assert result["size"] == 4, result
        assert result["epoch"] == 1, result  # exactly one relaunch


def test_stall_deadline_aborts_instead_of_hanging():
    """Companion acceptance test: a permanently-absent rank converts into
    RanksAbortedError on the healthy rank within the stall deadline —
    never the reference's infinite hang. (Python controller pinned here;
    the native wrapper's client-side escalation runs in
    test_multiprocess.py's CONTROLLERS battery.)"""
    from horovod_tpu.runner.launcher import launch

    rc = launch(
        [sys.executable, _WORKER, "stall_abort"], np=2,
        host_data_plane=True, job_timeout_s=90.0,
        env_extra={"HOROVOD_STALL_WARNING_TIME": "1",
                   "HOROVOD_STALL_SHUTDOWN_TIME_S": "2",
                   "HOROVOD_CYCLE_TIME": "2",
                   "HOROVOD_NATIVE_CONTROLLER": "0"})
    assert rc == 0


# -- slow tier: multi-restart soak + exhaustion ------------------------------

def _flaky_until_epoch_fn(heal_epoch):
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch

    hvd.init()
    if world_epoch() < heal_epoch and hvd.rank() == 1:
        os._exit(11)  # a crashing worker, not a user exception
    out = {"rank": hvd.rank(), "epoch": world_epoch()}
    hvd.shutdown()
    return out


@pytest.mark.slow
def test_run_elastic_multi_restart_soak():
    """Rank 1 crashes on epochs 0 and 1, heals on epoch 2: two relaunches
    with backoff, no blacklisting at slot_fail_limit=3."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _flaky_until_epoch_fn, args=(2,), np=3, min_np=2,
        max_restarts=3, backoff_s=0.1, timeout_s=120.0,
        start_timeout_s=120.0, slot_fail_limit=3)
    assert [r["rank"] for r in results] == [0, 1, 2]
    assert all(r["epoch"] == 2 for r in results)


def _always_crashing_fn():
    import os
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 0:
        print("permanent failure on this slot", file=sys.stderr,
              flush=True)
        os._exit(7)
    hvd.shutdown()
    return "ok"


@pytest.mark.slow
def test_run_elastic_exhausts_restart_budget():
    from horovod_tpu.elastic import ElasticExhaustedError
    from horovod_tpu.runner import run_elastic

    with pytest.raises(ElasticExhaustedError) as excinfo:
        run_elastic(_always_crashing_fn, np=2, min_np=1, max_restarts=1,
                    backoff_s=0.1, timeout_s=120.0, start_timeout_s=120.0)
    # the exhaustion error surfaces the dead rank's captured stderr
    assert "permanent failure on this slot" in str(excinfo.value)


def _user_bug_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd

    hvd.init()
    failing = hvd.rank() == 0
    hvd.shutdown()
    if failing:
        raise KeyError("deterministic application bug")
    return "ok"


@pytest.mark.slow
def test_run_elastic_fails_fast_on_user_exception():
    """A user-code exception is NOT a world fault: no retries, no
    blacklisting — it propagates on the first attempt."""
    import time

    from horovod_tpu.runner import run_elastic
    from horovod_tpu.runner.run_api import WorkerFailedError

    t0 = time.monotonic()
    with pytest.raises(WorkerFailedError) as excinfo:
        run_elastic(_user_bug_fn, np=2, min_np=1, max_restarts=3,
                    backoff_s=5.0, timeout_s=120.0, start_timeout_s=120.0)
    assert "deterministic application bug" in str(excinfo.value)
    # fail-fast: nowhere near max_restarts x (attempt + backoff)
    assert time.monotonic() - t0 < 60.0
