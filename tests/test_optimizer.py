"""DistributedOptimizer semantics (reference: ``test/test_torch.py`` optimizer
machinery + ``horovod/torch/__init__.py:65-198``)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def test_eager_matches_plain_optax(hvd):
    """Size-1 world: wrapped optimizer must match the inner optimizer."""
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros(3)}
    grads = {"w": jnp.full((3, 3), 0.5), "b": jnp.ones(3)}

    inner = optax.sgd(0.1)
    dist = hvd.DistributedOptimizer(optax.sgd(0.1))

    s0 = inner.init(params)
    u0, _ = inner.update(grads, s0, params)
    s1 = dist.init(params)
    u1, _ = dist.update(grads, s1, params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        u0, u1)


def test_spmd_grad_averaging(hvd):
    """Per-shard gradients differ; updates must equal mean-gradient SGD."""
    mesh = data_parallel_mesh()
    dist = hvd.DistributedOptimizer(optax.sgd(1.0), axis_name=DATA_AXIS)
    grads_per_shard = jnp.arange(8.0, dtype=jnp.float32)  # shard i -> grad i

    def step(g):
        params = jnp.zeros(())
        state = dist.init(params)
        updates, _ = dist.update(g[0], state, params)
        return updates

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P()))(grads_per_shard)
    np.testing.assert_allclose(np.asarray(out), -3.5)  # -mean(0..7)


def test_backward_passes_per_step_eager(hvd):
    """Delay-counter accumulation (``torch/__init__.py:71-73,114-130``):
    no update for N-1 passes, then one update from the accumulated grads."""
    dist = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = jnp.zeros(3)
    state = dist.init(params)
    g = jnp.ones(3)

    u1, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u1), 0.0)  # accumulating
    u2, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u2), -2.0)  # sum of 2 passes
    u3, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u3), 0.0)  # counter reset


def test_allreduce_gradients_tree(hvd):
    grads = {"a": np.ones(4, np.float32), "b": np.full((2, 2), 3.0, np.float32)}
    out = hvd.allreduce_gradients(grads)
    np.testing.assert_array_equal(np.asarray(out["a"]), grads["a"])
    np.testing.assert_array_equal(np.asarray(out["b"]), grads["b"])


def test_end_to_end_train_step_spmd(hvd):
    """Minimum end-to-end slice (SURVEY §7 step 4): data-parallel train step
    over the 8-device mesh with a tiny MLP; loss must decrease and params
    must stay replica-identical."""
    mesh = data_parallel_mesh()
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name=DATA_AXIS)

    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (4, 1)) * 0.1
    xs = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    ys = xs @ jnp.array([[1.0], [-2.0], [0.5], [3.0]])

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    def train_step(w, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(w, x, y)
        updates, opt_state = opt.update(grads, opt_state, w)
        # metric averaging across replicas, like MetricAverageCallback
        loss = jax.lax.pmean(loss, DATA_AXIS)
        return optax.apply_updates(w, updates), opt_state, loss

    sharded_step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P())))

    opt_state = opt.init(w)
    losses = []
    for _ in range(20):
        w, opt_state, loss = sharded_step(w, opt_state, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


class _LogCapture(logging.Handler):
    """LOG has propagate=False, so pytest's caplog never sees its records;
    capture by attaching directly."""

    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


def test_hierarchical_knob_warns_when_all_leaves_presummed(hvd):
    """Round-4 verdict weak #2: with the hierarchical knob on, a
    vma-tracked step's replicated-param cotangents arrive pre-summed and
    the factored route silently never fires — the user must get a warning
    naming the check_vma=False remedy. Legacy tracing (check_vma=False)
    routes every leaf through the factored path and must stay silent."""
    if not hasattr(jax, "typeof"):
        # The warning's TRIGGER is vma tracking pre-summing replicated
        # cotangents — a JAX without vma value types (jax.typeof; this
        # image's 0.4.37, where the compat shim also forces
        # check_rep=False) can never produce it, so asserting the warning
        # here would test a code path the runtime cannot reach. The
        # silent legacy half is covered by every hierarchical test in
        # this file.
        pytest.skip(
            "vma tracking does not exist on this JAX (no jax.typeof): "
            "pre-summed cotangents — the inert-route warning's trigger — "
            "cannot occur; _vma_tracking_active correctly reports legacy "
            "tracing and the factored route always fires")
    from jax.sharding import Mesh

    from horovod_tpu.core.logging import LOG

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dcn", "ici"))

    def reduce_fn(g):
        return hvd.allreduce_gradients(g, axis_name=("dcn", "ici"),
                                       hierarchical=True)

    for check_vma, expect_warning in ((True, True), (False, False)):
        cap = _LogCapture()
        LOG.addHandler(cap)
        try:
            out = jax.jit(shard_map(
                reduce_fn, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=check_vma))(jnp.ones(8))
            jax.block_until_ready(out)
        finally:
            LOG.removeHandler(cap)
        warned = any("factored hierarchical route is inert" in m
                     for m in cap.messages)
        assert warned == expect_warning, (check_vma, cap.messages)


def test_hierarchical_build_init_divergence_warns(monkeypatch):
    """Round-4 verdict weak #4: a step traced before hvd.init() resolves
    the hierarchical knob from the env and keeps that routing baked in; if
    the world then pins a different value, init must warn — and stay silent
    when build-time and pinned resolutions agree."""
    import horovod_tpu as hvd_mod
    from horovod_tpu import optimizers
    from horovod_tpu.core.logging import LOG

    assert not hvd_mod.is_initialized()

    def build_then_init(env_at_build, env_at_init):
        if env_at_build is None:
            monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE",
                               raising=False)
        else:
            monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE",
                               env_at_build)
        optimizers._prebuild_hierarchical_resolutions.clear()
        optimizers._use_hierarchical(("dcn", "ici"), None)  # "build" a step
        if env_at_init is None:
            monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE",
                               raising=False)
        else:
            monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", env_at_init)
        cap = _LogCapture()
        LOG.addHandler(cap)
        try:
            hvd_mod.init()
            hvd_mod.shutdown()
        finally:
            LOG.removeHandler(cap)
            optimizers._prebuild_hierarchical_resolutions.clear()
        return any("built before hvd.init()" in m for m in cap.messages)

    assert build_then_init(env_at_build=None, env_at_init="1") is True
    assert build_then_init(env_at_build="1", env_at_init=None) is True
    assert build_then_init(env_at_build="1", env_at_init="1") is False
    assert build_then_init(env_at_build=None, env_at_init=None) is False
