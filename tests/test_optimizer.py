"""DistributedOptimizer semantics (reference: ``test/test_torch.py`` optimizer
machinery + ``horovod/torch/__init__.py:65-198``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd
from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh


def test_eager_matches_plain_optax(hvd):
    """Size-1 world: wrapped optimizer must match the inner optimizer."""
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros(3)}
    grads = {"w": jnp.full((3, 3), 0.5), "b": jnp.ones(3)}

    inner = optax.sgd(0.1)
    dist = hvd.DistributedOptimizer(optax.sgd(0.1))

    s0 = inner.init(params)
    u0, _ = inner.update(grads, s0, params)
    s1 = dist.init(params)
    u1, _ = dist.update(grads, s1, params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        u0, u1)


def test_spmd_grad_averaging(hvd):
    """Per-shard gradients differ; updates must equal mean-gradient SGD."""
    mesh = data_parallel_mesh()
    dist = hvd.DistributedOptimizer(optax.sgd(1.0), axis_name=DATA_AXIS)
    grads_per_shard = jnp.arange(8.0, dtype=jnp.float32)  # shard i -> grad i

    def step(g):
        params = jnp.zeros(())
        state = dist.init(params)
        updates, _ = dist.update(g[0], state, params)
        return updates

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P(DATA_AXIS),
                            out_specs=P()))(grads_per_shard)
    np.testing.assert_allclose(np.asarray(out), -3.5)  # -mean(0..7)


def test_backward_passes_per_step_eager(hvd):
    """Delay-counter accumulation (``torch/__init__.py:71-73,114-130``):
    no update for N-1 passes, then one update from the accumulated grads."""
    dist = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = jnp.zeros(3)
    state = dist.init(params)
    g = jnp.ones(3)

    u1, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u1), 0.0)  # accumulating
    u2, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u2), -2.0)  # sum of 2 passes
    u3, state = dist.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(u3), 0.0)  # counter reset


def test_allreduce_gradients_tree(hvd):
    grads = {"a": np.ones(4, np.float32), "b": np.full((2, 2), 3.0, np.float32)}
    out = hvd.allreduce_gradients(grads)
    np.testing.assert_array_equal(np.asarray(out["a"]), grads["a"])
    np.testing.assert_array_equal(np.asarray(out["b"]), grads["b"])


def test_end_to_end_train_step_spmd(hvd):
    """Minimum end-to-end slice (SURVEY §7 step 4): data-parallel train step
    over the 8-device mesh with a tiny MLP; loss must decrease and params
    must stay replica-identical."""
    mesh = data_parallel_mesh()
    opt = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name=DATA_AXIS)

    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (4, 1)) * 0.1
    xs = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    ys = xs @ jnp.array([[1.0], [-2.0], [0.5], [3.0]])

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    def train_step(w, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(w, x, y)
        updates, opt_state = opt.update(grads, opt_state, w)
        # metric averaging across replicas, like MetricAverageCallback
        loss = jax.lax.pmean(loss, DATA_AXIS)
        return optax.apply_updates(w, updates), opt_state, loss

    sharded_step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P())))

    opt_state = opt.init(w)
    losses = []
    for _ in range(20):
        w, opt_state, loss = sharded_step(w, opt_state, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1
