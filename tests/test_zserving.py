"""Inference serving plane (docs/serving.md).

Tier-1 pins: the padding-bucket identity convention and edge ladder; the
continuous micro-batcher's packing/fairness/fill accounting; the
deadline-aware admission contract (429/503 + Retry-After, structured
503s carrying the relaunch epoch); the shared ``obs.httpd`` machinery
(route table, error mapping, the metrics endpoint sharing it); the
gateway end-to-end against an IN-PROCESS worker loop (batched results
bit-exact vs single dispatch, raw tensor bodies, clean stop); the
serving knob ladder and fault grammar. The 2-process acceptance battery
— kill-mid-batch through the elastic driver, the serving chaos cells,
the dryrun — runs under ``slow``.

Named ``test_zserving`` deliberately: the tier-1 budget truncates
alphabetically at ~870 s (ROADMAP note), and this module's subprocess
tests must sort past that point; each tier-1 test here stays in
single-digit seconds.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from horovod_tpu.serving import (
    AdmissionError,
    MicroBatcher,
    ServingPlane,
    Ticket,
    bucket_key,
    derive_edges,
    pad_to_edge,
    parse_serving_fault,
    serve_worker,
)

pytestmark = pytest.mark.serving


# -- buckets / batcher (tier 1) -----------------------------------------------


def test_bucket_key_identity_convention():
    """name/dtype/shape, the PR-3 response-cache identity convention."""
    key = bucket_key("mlp", np.float32, (4, 8))
    assert key == ("mlp", "float32", (4, 8))
    assert bucket_key("mlp", "float32", [4, 8]) == key
    assert bucket_key("mlp", np.float16, (4, 8)) != key
    assert bucket_key("mlp", np.float32, (8, 4)) != key
    assert bucket_key("other", np.float32, (4, 8)) != key


def test_edge_ladder_and_padding():
    assert derive_edges(8) == (1, 2, 4, 8)
    assert derive_edges(8, ratio=4.0) == (1, 4, 8)
    assert derive_edges(6) == (1, 2, 4, 6)  # always ends at batch_max
    assert derive_edges(8, explicit=(2, 4, 16)) == (2, 4, 8)
    assert pad_to_edge(3, (1, 2, 4, 8)) == 4
    assert pad_to_edge(1, (1, 2, 4, 8)) == 1
    assert pad_to_edge(9, (1, 2, 4, 8)) == 8  # never past the last edge


def _ticket(name="m", value=0.0, shape=(4,), deadline_s=30.0):
    array = np.full(shape, value, np.float32)
    return Ticket(bucket_key(name, array.dtype, array.shape), array,
                  deadline_s)


def test_batcher_packs_fifo_and_caps():
    batcher = MicroBatcher(batch_max=4)
    tickets = [_ticket(value=float(i)) for i in range(5)]
    for ticket in tickets:
        batcher.enqueue(ticket)
    key, got, padded = batcher.next_batch(timeout_s=0.1)
    assert [t.array[0] for t in got] == [0.0, 1.0, 2.0, 3.0]
    assert padded == 4
    batch = batcher.pack(got, padded)
    assert batch.shape == (4, 4) and batch.dtype == np.float32
    key2, got2, padded2 = batcher.next_batch(timeout_s=0.1)
    assert key2 == key and [t.array[0] for t in got2] == [4.0]
    assert padded2 == 1
    assert batcher.next_batch(timeout_s=0.05) is None
    assert batcher.depth == 0
    # emptied buckets are removed outright: client-controlled shapes
    # must not leave an ever-growing scan set behind
    assert batcher._queues == {}


def test_batcher_partial_batch_pads_to_edge_and_records_fill():
    batcher = MicroBatcher(batch_max=8)
    for i in range(3):
        batcher.enqueue(_ticket(value=float(i)))
    _, got, padded = batcher.next_batch(timeout_s=0.1)
    assert len(got) == 3 and padded == 4  # 3 pads to edge 4
    batch = batcher.pack(got, padded)
    assert batch.shape[0] == 4
    np.testing.assert_array_equal(batch[3], np.zeros(4, np.float32))


def test_batcher_buckets_never_mix_and_oldest_head_wins():
    batcher = MicroBatcher(batch_max=8)
    a0 = _ticket(name="a", value=1.0)
    time.sleep(0.002)
    b0 = _ticket(name="b", value=2.0, shape=(8,))
    batcher.enqueue(b0)
    batcher.enqueue(a0)  # enqueue order != arrival (t0) order
    key, got, _ = batcher.next_batch(timeout_s=0.1)
    assert key == a0.key and got == [a0]  # oldest head, not first queue
    key2, got2, _ = batcher.next_batch(timeout_s=0.1)
    assert key2 == b0.key and got2 == [b0]


def test_batcher_skips_closed_tickets():
    batcher = MicroBatcher(batch_max=4)
    dead = _ticket(value=1.0)
    live = _ticket(value=2.0)
    batcher.enqueue(dead)
    batcher.enqueue(live)
    assert dead.claim_timeout(epoch=0)
    _, got, padded = batcher.next_batch(timeout_s=0.1)
    assert got == [live] and padded == 1


def test_ticket_state_transitions_are_one_way():
    ticket = _ticket()
    assert ticket.complete(np.ones(4, np.float32))
    assert not ticket.fail(503, "late")  # loser drops its outcome
    assert not ticket.claim_timeout()
    assert ticket.state == "done" and ticket.status == 200
    ticket2 = _ticket()
    assert ticket2.claim_timeout(epoch=3)
    assert not ticket2.complete(np.ones(4, np.float32))
    assert ticket2.status == 503 and ticket2.epoch == 3
    assert ticket2.output is None


def test_serving_fault_grammar():
    assert parse_serving_fault("") is None
    assert parse_serving_fault("kill@rank1:batch2") == (1, 2, 0)
    assert parse_serving_fault("kill@rank0:batch7@epoch2") == (0, 7, 2)
    with pytest.raises(ValueError, match="kill@rankN:batchM"):
        parse_serving_fault("kil@rank1:batch2")
    with pytest.raises(ValueError, match="1-based"):
        parse_serving_fault("kill@rank1:batch0")


def test_serving_knobs_ladder_and_pinning():
    from horovod_tpu.tune.policy import (
        KNOB_SERVING_BATCH,
        KNOB_SERVING_EDGES,
        TuningPolicy,
        serving_knobs,
    )

    knobs = {k.name: k for k in serving_knobs(8, 2.0)}
    assert knobs[KNOB_SERVING_BATCH].current == 8.0
    assert 128.0 in knobs[KNOB_SERVING_BATCH].values
    assert knobs[KNOB_SERVING_EDGES].values == (2.0, 4.0)
    pinned = {k.name: k for k in serving_knobs(
        8, 2.0, batch_max_explicit=True, edges_explicit=True)}
    assert all(k.pinned for k in pinned.values())
    # splice-in: a live value off the ladder starts the cursor there
    assert serving_knobs(6, 2.0)[0].current == 6.0
    # the policy drives them like any other knob set
    policy = TuningPolicy(serving_knobs(8, 2.0), window=1, cooldown=0)
    decision = None
    for _ in range(4):
        decision = decision or policy.observe(1e6, 1e3)
    assert decision is not None and decision.action == "retune"
    assert decision.knob in (KNOB_SERVING_BATCH, KNOB_SERVING_EDGES)


# -- shared HTTP machinery (tier 1; the satellite factoring) ------------------


def test_httpd_routes_errors_and_close():
    from horovod_tpu.obs.httpd import (
        HttpError,
        HttpResponse,
        LoopbackHTTPD,
    )

    def ok(_q, _h, body):
        return HttpResponse(200, "text/plain", b"hi " + body)

    def boom(_q, _h, _b):
        raise RuntimeError("kaput")

    def reject(_q, _h, _b):
        raise HttpError(429, "slow down", headers={"Retry-After": "2"})

    httpd = LoopbackHTTPD("t", 0, {("POST", "/ok"): ok,
                                   ("GET", "/boom"): boom,
                                   ("GET", "/reject"): reject})
    base = f"http://127.0.0.1:{httpd.port}"
    resp = urllib.request.urlopen(urllib.request.Request(
        f"{base}/ok", data=b"there"), timeout=5)
    assert resp.status == 200 and resp.read() == b"hi there"
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(f"{base}/nope", timeout=5)
    assert err.value.code == 404
    assert b"/ok" in err.value.read()  # the 404 lists served routes
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(f"{base}/boom", timeout=5)
    assert err.value.code == 500 and b"kaput" in err.value.read()
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(f"{base}/reject", timeout=5)
    assert err.value.code == 429
    assert err.value.headers["Retry-After"] == "2"
    httpd.close()
    httpd.close()  # idempotent


def test_httpd_close_cuts_keepalive_connections():
    """A closed server must stop ANSWERING, not just stop accepting:
    under HTTP/1.1 keep-alive a connected client's handler thread loops
    independently of the accept loop, and re-registration on a fixed
    port (exposition.serve after re-init) must not leave old clients
    pinned to the torn-down instance."""
    import http.client

    from horovod_tpu.obs.httpd import HttpResponse, LoopbackHTTPD

    httpd = LoopbackHTTPD("t", 0, {
        ("GET", "/ping"): lambda q, h, b: HttpResponse(body=b"pong")})
    conn = http.client.HTTPConnection("127.0.0.1", httpd.port, timeout=5)
    conn.request("GET", "/ping")
    assert conn.getresponse().read() == b"pong"  # keep-alive established
    httpd.close()
    with pytest.raises((ConnectionError, http.client.HTTPException,
                        OSError)):
        conn.request("GET", "/ping")
        conn.getresponse()
    conn.close()


def test_metrics_endpoint_rides_the_shared_httpd():
    """One implementation, two route sets: the exposition server IS a
    LoopbackHTTPD carrying metrics_routes (the satellite's claim)."""
    from horovod_tpu.obs.exposition import MetricsServer
    from horovod_tpu.obs.httpd import LoopbackHTTPD

    provider = lambda: {"world": {}, "ranks": {}}  # noqa: E731
    server = MetricsServer(0, provider)
    try:
        assert isinstance(server._httpd, LoopbackHTTPD)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics.json",
            timeout=5).read()
        assert json.loads(body) == {"world": {}, "ranks": {}}
    finally:
        server.close()


# -- plane + gateway against an in-process worker (tier 1) --------------------

_W = (np.arange(64, dtype=np.float32).reshape(8, 8) % 5) - 2


def _model(x):
    return x @ _W + 1.0


def _expected(x):
    return x @ _W + 1.0


def _start_world(plane, models=None, size=1, **worker_kw):
    """In-process worker thread(s) dialing the plane over loopback — the
    full wire without subprocesses, the tier-1 trick."""
    from horovod_tpu.serving import ServingAbortedError

    def _tolerant(**kw):
        try:
            serve_worker(models or {"demo": _model}, **kw)
        except ServingAbortedError:
            pass  # world_down tests abort workers on purpose

    threads = []
    for rank in range(size):
        thread = threading.Thread(
            target=_tolerant,
            kwargs=dict(addr=("127.0.0.1", plane.service_port),
                        secret=plane.secret, rank=rank, size=size,
                        epoch=plane.current_epoch, jit=False,
                        **worker_kw),
            daemon=True)
        thread.start()
        threads.append(thread)
    deadline = time.monotonic() + 10.0
    while not plane.stats()["armed"]:
        assert time.monotonic() < deadline, plane.stats()
        time.sleep(0.01)
    return threads


def _post(plane, inputs, name="demo", timeout=15, deadline_ms=None):
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Serving-Deadline-Ms"] = str(deadline_ms)
    req = urllib.request.Request(
        f"http://127.0.0.1:{plane.gateway_port}/v1/infer",
        data=json.dumps({"name": name,
                         "inputs": np.asarray(inputs).tolist()}).encode(),
        headers=headers)
    return urllib.request.urlopen(req, timeout=timeout)


def test_gateway_end_to_end_json_and_raw():
    plane = ServingPlane(gateway_port=0, batch_max=4, slo_ms=5000,
                         deadline_ms=10000)
    try:
        threads = _start_world(plane)
        x = np.arange(8, dtype=np.float32)
        resp = _post(plane, x)
        assert resp.status == 200
        assert resp.headers["X-Serving-Epoch"] == "0"
        out = np.asarray(json.loads(resp.read())["outputs"], np.float32)
        np.testing.assert_array_equal(out, _expected(x))
        # raw tensor body round trip
        req = urllib.request.Request(
            f"http://127.0.0.1:{plane.gateway_port}/v1/infer",
            data=x.tobytes(),
            headers={"Content-Type": "application/octet-stream",
                     "X-Tensor-Name": "demo",
                     "X-Tensor-Dtype": "float32",
                     "X-Tensor-Shape": "8"})
        resp = urllib.request.urlopen(req, timeout=15)
        assert resp.headers["X-Tensor-Shape"] == "8"
        np.testing.assert_array_equal(
            np.frombuffer(resp.read(), np.float32), _expected(x))
        # healthz reflects the live knobs
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{plane.gateway_port}/v1/healthz",
            timeout=5).read())
        assert health["armed"] and health["serving_batch_max"] == 4
        # the co-hosted metrics route set serves this process's registry
        from horovod_tpu.obs.exposition import parse_prometheus

        text = urllib.request.urlopen(
            f"http://127.0.0.1:{plane.gateway_port}/metrics",
            timeout=5).read().decode()
        families = parse_prometheus(text)
        assert families["horovod_serving_requests_total"] == "counter"
        assert families["horovod_serving_latency_seconds"] == "histogram"
        plane.stop()
        for thread in threads:
            thread.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
    finally:
        plane.close()


def test_batched_results_bit_exact_vs_single_dispatch():
    """The tentpole exactness claim at unit scale: concurrent requests
    packed into real multi-row batches return the same bits as
    batch_max=1 dispatch (integer-valued float32 matmul is exact)."""
    plane = ServingPlane(gateway_port=0, batch_max=4, slo_ms=10000,
                         deadline_ms=20000)
    try:
        _start_world(plane)
        inputs = [np.full(8, float(i + 1), np.float32) for i in range(10)]
        batched = [None] * len(inputs)

        def _client(i):
            batched[i] = np.asarray(
                json.loads(_post(plane, inputs[i]).read())["outputs"],
                np.float32)

        clients = [threading.Thread(target=_client, args=(i,))
                   for i in range(len(inputs))]
        for thread in clients:
            thread.start()
        for thread in clients:
            thread.join(timeout=30)
        assert plane.stats()["max_batch_real"] >= 2, plane.stats()
        plane.set_batch_max(1)
        for i, x in enumerate(inputs):
            single = np.asarray(
                json.loads(_post(plane, x).read())["outputs"], np.float32)
            np.testing.assert_array_equal(batched[i], single)
            np.testing.assert_array_equal(single, _expected(x))
    finally:
        plane.close()


def test_unknown_model_fails_structurally_500():
    plane = ServingPlane(gateway_port=0, batch_max=2, slo_ms=5000,
                         deadline_ms=10000)
    try:
        _start_world(plane)
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(plane, np.ones(8, np.float32), name="nosuch")
        assert err.value.code == 500
        assert "nosuch" in json.loads(err.value.read())["error"]
        # the world keeps serving after a structural failure
        resp = _post(plane, np.arange(8, dtype=np.float32))
        assert resp.status == 200
    finally:
        plane.close()


def test_deadline_claim_never_hangs():
    """A request whose deadline passes unanswered gets a 503 from its
    OWN gateway thread — the never-a-hang guarantee needs no world
    cooperation (here: no world at all past admission... so use a slow
    model instead)."""
    slow = {"demo": lambda x: (time.sleep(0.6), x)[1]}
    plane = ServingPlane(gateway_port=0, batch_max=2, slo_ms=60000,
                         deadline_ms=60000)
    try:
        _start_world(plane, models=slow)
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(plane, np.ones(4, np.float32), deadline_ms=150)
        elapsed = time.monotonic() - t0
        assert err.value.code == 503
        assert "deadline" in json.loads(err.value.read())["error"]
        assert elapsed < 2.0, elapsed
    finally:
        plane.close()


def test_malformed_requests_400():
    plane = ServingPlane(gateway_port=0)
    try:
        for body, headers in (
                (b"not json", {"Content-Type": "application/json"}),
                (json.dumps({"inputs": [1]}).encode(),
                 {"Content-Type": "application/json"}),
                (b"\x00" * 7, {"Content-Type":
                               "application/octet-stream"}),
                (json.dumps({"name": "demo", "inputs": [1.0]}).encode(),
                 {"Content-Type": "application/json",
                  "X-Serving-Deadline-Ms": "soon"})):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{plane.gateway_port}/v1/infer",
                    data=body, headers=headers), timeout=5)
            assert err.value.code == 400
    finally:
        plane.close()


# -- admission contract (tier 1) ----------------------------------------------


def test_admission_503_when_no_world_carries_epoch():
    plane = ServingPlane(gateway_port=0)
    try:
        plane.begin_epoch(3, 2)  # relaunching toward epoch 3, not armed
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(plane, np.ones(4, np.float32))
        assert err.value.code == 503
        assert err.value.headers["Retry-After"]
        body = json.loads(err.value.read())
        assert body["epoch"] == 3
        assert "relaunching" in body["error"]
    finally:
        plane.close()


def test_admission_queue_cap_503_and_slo_429():
    plane = ServingPlane(gateway_port=0, queue_max=2, slo_ms=1000)
    try:
        with plane._cond:  # arm without a world: admission-only test
            plane._armed = True
            plane._world = 1
        plane._ema_batch_s = 10.0  # nothing drains; estimates are huge
        plane.submit("m", np.ones(4, np.float32))
        with pytest.raises(AdmissionError) as err:
            plane.submit("m", np.ones(4, np.float32))
        assert err.value.status == 429  # SLO budget exceeded first
        assert err.value.retry_after_s > 0
        plane._ema_batch_s = 1e-4  # fast world, but the cap still bites
        plane.submit("m", np.ones(4, np.float32))
        with pytest.raises(AdmissionError) as err:
            plane.submit("m", np.ones(4, np.float32))
        assert err.value.status == 503
        assert "queue full" in err.value.message
    finally:
        plane.close()


def test_world_down_drains_requeues_and_rearms():
    """The failover matrix at unit scale: world_down fails
    short-deadline in-flight tickets with a structured 503 (epoch
    attached), requeues long-deadline ones, and a re-armed epoch serves
    the requeued ticket to completion."""
    plane = ServingPlane(gateway_port=0, batch_max=2, slo_ms=10000,
                         deadline_ms=30000)
    try:
        threads = _start_world(plane, models={
            "demo": lambda x: (time.sleep(0.4), _model(x))[1]})
        done = []
        thread = threading.Thread(
            target=lambda: done.append(_post(plane, np.ones(
                8, np.float32), timeout=30).status), daemon=True)
        thread.start()
        deadline = time.monotonic() + 5
        while plane.stats()["inflight"] == 0:  # dispatched, not finished
            assert time.monotonic() < deadline
            time.sleep(0.01)
        plane.world_down("test kills the world")
        stats = plane.stats()
        assert not stats["armed"] and "test kills" in stats["down_reason"]
        # admission while down: structured 503 + epoch
        with pytest.raises(AdmissionError) as err:
            plane.submit("demo", np.ones(8, np.float32))
        assert err.value.status == 503
        for t in threads:
            t.join(timeout=10)  # workers aborted (rendezvous torn down)
        plane.begin_epoch(1, 1)
        _start_world(plane)  # fast model this time
        thread.join(timeout=20)
        assert done == [200]  # the requeued ticket completed after re-arm
        assert plane.stats()["epoch"] == 1
    finally:
        plane.close()


def test_stop_with_batch_in_flight_drains_clean():
    """stop() racing a dispatched batch must DRAIN it, not strand it:
    every rank still fetches and votes on an already-dispatched frame
    (only the next ordinal answers "stop"), so the in-flight request
    completes 200 and both workers exit stopped — no spurious
    world-fault, no deadline-burned 503."""
    slow = {"demo": lambda x: (time.sleep(0.3), _model(x))[1]}
    plane = ServingPlane(gateway_port=0, batch_max=2, slo_ms=10000,
                         deadline_ms=20000)
    try:
        threads = _start_world(plane, models=slow, size=2)
        done = []
        client = threading.Thread(
            target=lambda: done.append(_post(plane, np.arange(
                8, dtype=np.float32), timeout=20).status), daemon=True)
        client.start()
        deadline = time.monotonic() + 5
        while plane.stats()["inflight"] == 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        plane.stop()  # mid-execution: the batch is dispatched, unvoted
        client.join(timeout=20)
        assert done == [200], done
        for thread in threads:
            thread.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
    finally:
        plane.close()


# -- 2-process acceptance battery (slow) --------------------------------------


@pytest.mark.slow
def test_serving_chaos_drop_cell_heals():
    from horovod_tpu.chaos.matrix import SERVING_GRID, run_serving_cell

    spec, fault, expect = SERVING_GRID[0]
    cell = run_serving_cell(spec, fault, expect, requests=8)
    assert cell["outcome"] == expect, cell


@pytest.mark.slow
def test_serving_kill_mid_batch_recovers():
    """Acceptance: a rank killed mid-batch escalates through the elastic
    driver; every request issued around the kill resolves as 200 or a
    structured 503 carrying a relaunch epoch — never a hang."""
    from horovod_tpu.chaos.matrix import run_serving_cell

    cell = run_serving_cell("", "kill@rank1:batch2@epoch0", "recovered",
                            requests=10)
    assert cell["outcome"] == "recovered", cell
    codes = [r[1] for r in cell["responses"]]
    assert 200 in codes  # some completed (before the kill or after re-arm)
    for _i, code, detail in cell["responses"]:
        if code == 503:
            assert detail is not None  # structured: epoch attached


@pytest.mark.slow
def test_dryrun_serving_certifies():
    """The driver's acceptance artifact, exactly as __graft_entry__ runs
    it: batched-vs-single bit-exactness, kill-mid-batch recovery, clean
    world zero errors."""
    import __graft_entry__ as graft

    graft.dryrun_serving(2)
