"""Callback parity tests (reference: ``horovod/_keras/callbacks.py``
behaviors exercised via ``test_keras.py``-style assertions)."""

import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.callbacks import (
    BroadcastGlobalVariablesCallback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    TrainLoop,
    warmup_schedule,
)


def test_metric_average_size1(hvd):
    cb = MetricAverageCallback()
    logs = {"loss": 2.5, "acc": 0.5}
    cb.on_epoch_end(0, TrainLoop(), logs)
    assert logs == {"loss": 2.5, "acc": 0.5}  # size-1: untouched


def test_lr_schedule_staircase(hvd):
    state = TrainLoop(learning_rate=0.1)
    cb = LearningRateScheduleCallback(
        initial_lr=0.1, multiplier=lambda e: 0.5 ** e, start_epoch=1)
    cb.on_epoch_begin(0, state)
    assert state.learning_rate == 0.1  # before start_epoch
    cb.on_epoch_begin(2, state)
    assert state.learning_rate == pytest.approx(0.1 * 0.25)


def test_lr_warmup_progression(hvd):
    # 8 virtual devices: warmup target = initial * 8
    state = TrainLoop(learning_rate=0.1)
    cb = LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=2,
                                    steps_per_epoch=10)
    loop = CallbackList([cb])
    loop.on_epoch_begin(0, state)
    loop.on_batch_begin(0, state)
    assert state.learning_rate == pytest.approx(0.1)  # start at base lr
    loop.on_epoch_begin(1, state)
    loop.on_batch_begin(0, state)
    assert state.learning_rate == pytest.approx(0.1 * (1 + 0.5 * 7))
    loop.on_epoch_begin(2, state)
    loop.on_batch_begin(0, state)
    assert state.learning_rate == pytest.approx(0.8)  # full scale 0.1 * 8


def test_smooth_schedule_requires_steps_per_epoch(hvd):
    cb = LearningRateScheduleCallback(0.1, 2.0, staircase=False)
    with pytest.raises(ValueError, match="steps_per_epoch"):
        cb.on_batch_begin(0, TrainLoop())


def test_set_lr_updates_inject_hyperparams(hvd):
    import jax.numpy as jnp

    opt = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    params = {"w": jnp.ones(3)}
    state = TrainLoop(params=params, opt_state=opt.init(params),
                      learning_rate=0.1)
    state.set_lr(0.4)
    assert float(state.opt_state.hyperparams["learning_rate"]) == \
        pytest.approx(0.4)
    # the injected lr must actually drive the update
    updates, _ = opt.update({"w": jnp.ones(3)}, state.opt_state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.4, rtol=1e-6)


def test_broadcast_callback_size1(hvd):
    import jax.numpy as jnp

    params = {"w": jnp.ones(2)}
    opt = optax.sgd(0.1)
    state = TrainLoop(params=params, opt_state=opt.init(params))
    BroadcastGlobalVariablesCallback(0).on_train_begin(state)
    np.testing.assert_array_equal(np.asarray(state.params["w"]), 1.0)


def test_warmup_schedule_fn(hvd):
    sched = warmup_schedule(base_lr=0.1, steps_per_epoch=10, warmup_epochs=2,
                            target_scale=8.0)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(10)) == pytest.approx(0.1 * (1 + 0.5 * 7))
    assert float(sched(20)) == pytest.approx(0.8)
    assert float(sched(100)) == pytest.approx(0.8)  # clamps after warmup
