"""Black-box timeline test (reference: ``test/test_timeline.py:41-58``):
set HOROVOD_TIMELINE, run collectives, assert the Chrome-trace JSON contains
the negotiation/op/cycle markers."""

import json
import os

import numpy as np
import pytest


def test_timeline(tmp_path, monkeypatch):
    path = str(tmp_path / "timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")

    import horovod_tpu as hvd

    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        x = np.ones((16, 16), dtype=np.float32)
        hvd.allreduce(x, name="timeline_tensor")
        hvd.allgather(x, name="timeline_gather")
        hvd.broadcast(x, root_rank=0, name="timeline_bcast")
    finally:
        hvd.shutdown()  # flushes + closes the writer

    with open(path, encoding="utf-8") as fh:
        content = fh.read()
    assert "NEGOTIATE_ALLREDUCE" in content
    assert "ALLREDUCE" in content
    assert "NEGOTIATE_ALLGATHER" in content
    assert "NEGOTIATE_BROADCAST" in content
    assert "CYCLE_START" in content
    assert "timeline_tensor" in content
    records = json.loads(content)  # valid Chrome tracing JSON after close
    assert isinstance(records, list) and len(records) > 5


def test_counter_after_close_dropped_loudly(tmp_path, monkeypatch, caplog):
    """Edge case the obs.TimelineBridge relies on: a counter emitted
    after close() is dropped with a warning — never written to (or
    queued behind) the terminated file."""
    import logging

    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")  # python writer: the
    # test inspects the file; the closed-flag semantics are writer-agnostic
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "t.json"
    tl = Timeline(str(path))
    tl.counter("metrics/x", {"value": 1})
    tl.close()
    # core.logging sets propagate=False on the horovod_tpu logger, so
    # caplog's root handler never sees it — attach the handler directly
    logger = logging.getLogger("horovod_tpu")
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            tl.counter("metrics/x", {"value": 2})
            tl.counter("metrics/x", {"value": 3})
    finally:
        logger.removeHandler(caplog.handler)
    assert any("after close()" in r.getMessage() for r in caplog.records)
    records = [r for r in json.loads(path.read_text())
               if isinstance(r, dict) and r.get("ph") == "C"]
    assert [r["args"] for r in records] == [{"value": 1}]  # nothing late


def test_interleaved_counter_and_span_events_valid_json(tmp_path,
                                                        monkeypatch):
    """Edge case: counter records interleaved with span begin/end pairs
    (exactly what the bridge produces mid-cycle) must still close into
    valid Chrome-tracing JSON."""
    monkeypatch.setenv("HOROVOD_NATIVE_CORE", "0")
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "t.json"
    tl = Timeline(str(path), mark_cycles=True)
    tl.negotiate_start("t1", "allreduce")
    tl.counter("metrics/a", {"value": 1})
    tl.negotiate_end("t1")
    tl.start("t1", "allreduce")
    tl.counter("metrics/a", {"value": 2})
    tl.mark_cycle_start()
    tl.end("t1", shape=(4, 4))
    tl.counter("metrics/b", {"x": 1, "y": 2.5})
    tl.close()
    records = json.loads(path.read_text())
    assert isinstance(records, list)
    phases = [r.get("ph") for r in records if isinstance(r, dict) and r]
    assert phases.count("C") == 3
    assert phases.count("B") == 2 and phases.count("E") == 2
    assert "i" in phases  # the CYCLE_START instant survived interleaving
    for rec in records:
        if isinstance(rec, dict) and rec.get("ph") == "C":
            assert isinstance(rec["args"], dict)


def test_jax_profile_artifact(tmp_path, monkeypatch):
    """HOROVOD_JAX_PROFILE brackets init→shutdown with a jax.profiler
    trace on rank 0 — the on-device twin of the host timeline (SURVEY
    §5.1's 'pointers into the JAX profiler' mapping). Black-box like the
    timeline test: run ops, assert the XPlane artifact exists."""
    import glob

    import numpy as np

    import horovod_tpu as hvd

    prof_dir = str(tmp_path / "prof")
    monkeypatch.setenv("HOROVOD_JAX_PROFILE", prof_dir)
    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        hvd.allreduce(np.ones((8, 8), dtype=np.float32), name="prof_t")
    finally:
        hvd.shutdown()
    traces = glob.glob(prof_dir + "/**/*.xplane.pb", recursive=True)
    assert traces, f"no XPlane trace written under {prof_dir}"
