"""Black-box timeline test (reference: ``test/test_timeline.py:41-58``):
set HOROVOD_TIMELINE, run collectives, assert the Chrome-trace JSON contains
the negotiation/op/cycle markers."""

import json
import os

import numpy as np
import pytest


def test_timeline(tmp_path, monkeypatch):
    path = str(tmp_path / "timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")

    import horovod_tpu as hvd

    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        x = np.ones((16, 16), dtype=np.float32)
        hvd.allreduce(x, name="timeline_tensor")
        hvd.allgather(x, name="timeline_gather")
        hvd.broadcast(x, root_rank=0, name="timeline_bcast")
    finally:
        hvd.shutdown()  # flushes + closes the writer

    with open(path, encoding="utf-8") as fh:
        content = fh.read()
    assert "NEGOTIATE_ALLREDUCE" in content
    assert "ALLREDUCE" in content
    assert "NEGOTIATE_ALLGATHER" in content
    assert "NEGOTIATE_BROADCAST" in content
    assert "CYCLE_START" in content
    assert "timeline_tensor" in content
    records = json.loads(content)  # valid Chrome tracing JSON after close
    assert isinstance(records, list) and len(records) > 5


def test_jax_profile_artifact(tmp_path, monkeypatch):
    """HOROVOD_JAX_PROFILE brackets init→shutdown with a jax.profiler
    trace on rank 0 — the on-device twin of the host timeline (SURVEY
    §5.1's 'pointers into the JAX profiler' mapping). Black-box like the
    timeline test: run ops, assert the XPlane artifact exists."""
    import glob

    import numpy as np

    import horovod_tpu as hvd

    prof_dir = str(tmp_path / "prof")
    monkeypatch.setenv("HOROVOD_JAX_PROFILE", prof_dir)
    hvd.shutdown()  # pick up fresh env in a clean init
    hvd.init()
    try:
        hvd.allreduce(np.ones((8, 8), dtype=np.float32), name="prof_t")
    finally:
        hvd.shutdown()
    traces = glob.glob(prof_dir + "/**/*.xplane.pb", recursive=True)
    assert traces, f"no XPlane trace written under {prof_dir}"
