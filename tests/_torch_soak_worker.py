"""Torch front-end churn: many real train steps through the hook path.

The collectives engine has its own soaks; this one targets the torch
binding's stateful machinery under sustained stepping — gradient hooks
firing per backward, handle bookkeeping, ``backward_passes_per_step``
accumulation windows, fp16 wire compression, and EVERY step's
force-allreduce of the dead head's untouched parameters (the model
carries a layer that never feeds the loss, the reference
``test_force_allreduce`` situation) — with the cross-rank
identical-weights invariant (every step applies the world-averaged
gradient) checked every 10 steps."""
import os
import sys

os.environ.pop("JAX_PLATFORMS", None)
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import horovod_tpu as hvd

STEPS = int(os.environ.get("SOAK_STEPS", "120"))
ACCUM = 3  # backward_passes_per_step
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])

import torch

import horovod_tpu.torch as hvd_torch

hvd.init()
torch.manual_seed(77)  # same init everywhere


class Net(torch.nn.Module):
    def __init__(self) -> None:
        super().__init__()
        self.body = torch.nn.Sequential(
            torch.nn.Linear(6, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
        # dead head: registered, never feeds the loss — its grads stay
        # None and the optimizer must force-allreduce them EVERY step
        # (reference test_force_allreduce; scenario torch_unused is the
        # single-shot pin, this soaks it)
        self.dead_head = torch.nn.Linear(6, 3)

    def forward(self, x):
        return self.body(x)


model = Net()
opt = hvd_torch.DistributedOptimizer(
    torch.optim.SGD(model.parameters(), lr=0.05),
    named_parameters=model.named_parameters(),
    compression=hvd_torch.Compression.fp16,
    backward_passes_per_step=ACCUM)
hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
hvd_torch.broadcast_optimizer_state(opt, root_rank=0)

g = torch.Generator().manual_seed(123)  # same data stream shape-wise
for step_no in range(STEPS):
    opt.zero_grad()
    for micro in range(ACCUM):
        # rank-dependent data: averaging is what keeps ranks identical
        x = torch.randn(4, 6, generator=g) + rank * 0.1
        y = torch.randn(4, 2, generator=g)
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
    opt.step()
    if step_no % 10 == 0:
        # cross-rank weight equivalence: the product's core invariant
        flat = torch.cat([p.detach().reshape(-1)
                          for p in model.parameters()])
        gathered = hvd_torch.allgather(flat.unsqueeze(0),
                                       name=f"tw.eq.{step_no}")
        for r in range(size):
            np.testing.assert_allclose(
                gathered[r].numpy(), flat.numpy(), rtol=1e-4,
                err_msg=f"rank weights diverged at step {step_no}")

hvd.shutdown()
print(f"TORCHSOAK-OK rank {rank} steps={STEPS}", flush=True)
os._exit(0)
