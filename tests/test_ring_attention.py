"""Sequence/context parallelism: ring attention and Ulysses must match
dense attention exactly over the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import data_parallel_mesh
from horovod_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)

B, T, H, D = 2, 32, 8, 16  # global sequence 32 over 8 shards -> 4 local


def _qkv(seed):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(hvd, causal):
    mesh = data_parallel_mesh()
    q, k, v = _qkv(0)

    def ring(q, k, v):
        return ring_attention(q, k, v, "data", causal=causal)

    out = jax.jit(shard_map(
        ring, mesh=mesh,
        in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
        out_specs=P(None, "data")))(q, k, v)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(hvd, causal):
    mesh = data_parallel_mesh()
    q, k, v = _qkv(1)

    def uly(q, k, v):
        return ulysses_attention(q, k, v, "data", causal=causal)

    out = jax.jit(shard_map(
        uly, mesh=mesh,
        in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
        out_specs=P(None, "data")))(q, k, v)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads(hvd):
    mesh = data_parallel_mesh()
    q = jnp.ones((B, T, 6, D))  # 6 heads not divisible by 8

    def uly(q):
        return ulysses_attention(q, q, q, "data")

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(shard_map(uly, mesh=mesh, in_specs=P(None, "data"),
                          out_specs=P(None, "data")))(q)


def test_ring_attention_long_context_memory_shape(hvd):
    """Larger-than-dense case smoke: per-shard tensors stay O(T/S)."""
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, 256, 4, 8)).astype(np.float32))

    def ring(q):
        return ring_attention(q, q, q, "data", causal=True)

    out = jax.jit(shard_map(ring, mesh=mesh, in_specs=P(None, "data"),
                            out_specs=P(None, "data")))(q)
    ref = dense_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
