"""Controller scalability: cycle latency must stay bounded at 32 ranks.

The reference runs 5 ms negotiation cycles at 512 MPI ranks
(``operations.cc:2030``); this environment cannot host 512 processes, so the
stand-in is 32 threaded ranks driving one ``ControllerService`` — which
exercises exactly the coordinator-side serial work that would collapse first
(accept backlog, per-rank response serialization, rendezvous wakeups).

Regression history: before round 2 the service inherited socketserver's
backlog of 5 (SYN drops → 1 s retransmit stalls at 16+ simultaneous
connects) and pickled+HMAC'd the identical ResponseList once per rank; a
32-rank world saw >1 s worst-case cycles. With the fixes the same world
measures ~15 ms median / ~40 ms max on this hardware; the bounds below are
several-fold looser to absorb CI noise while still catching a collapse.
"""

from __future__ import annotations

import statistics
import threading
import time

import numpy as np
import pytest

from horovod_tpu.core.config import Config
from horovod_tpu.ops.controller import (
    ControllerClient,
    ControllerService,
    make_negotiator,
)
from horovod_tpu.ops.messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
)

# Subprocess/soak-heavy by design: excluded from the quick tier (-m "not soak").
pytestmark = pytest.mark.soak

SECRET = b"s" * 32


def _request(rank: int, name: str, shape=(64,)) -> Request:
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=shape, root_rank=-1)


def _drive_world(size: int, n_cycles: int, tensors_per_cycle: int):
    """Run a threaded world; return rank 0's per-cycle latencies (seconds)
    and every rank's final ResponseList for cross-rank identity checks."""
    cfg = Config.from_env()
    service = ControllerService(size, make_negotiator(size, cfg),
                                secret=SECRET, port=0)
    latencies: list[float] = []
    finals: dict[int, object] = {}
    errors: list[BaseException] = []

    def worker(rank: int) -> None:
        try:
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET)
            for c in range(n_cycles):
                requests = [_request(rank, f"t{c}_{i}")
                            for i in range(tensors_per_cycle)]
                t0 = time.perf_counter()
                out = client.cycle(rank, RequestList(rank=rank,
                                                     requests=requests))
                if rank == 0:
                    latencies.append(time.perf_counter() - t0)
                finals[rank] = out
            client.close()
        except BaseException as exc:  # noqa: BLE001 - surfaced by the test
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    service.shutdown()
    assert not errors, errors
    assert len(finals) == size
    return latencies, finals


def test_cycle_latency_bounded_at_32_ranks():
    latencies, finals = _drive_world(size=32, n_cycles=30,
                                     tensors_per_cycle=8)
    median = statistics.median(latencies)
    worst = max(latencies)
    assert median < 0.25, f"median cycle {median * 1e3:.1f} ms at 32 ranks"
    # The pre-fix failure mode was kernel SYN retransmits: ~1 s spikes.
    assert worst < 1.0, f"worst cycle {worst * 1e3:.0f} ms at 32 ranks"
    # Every rank decoded the identical (pre-framed) response list.
    names = [tuple(n for r in f.responses for n in r.tensor_names)
             for f in finals.values()]
    assert len(set(names)) == 1


def test_clean_client_close_is_not_a_rank_death():
    """A rank-identified client that detaches cleanly (close() without a
    negotiated world shutdown) must not poison the controller: later
    clients for the same ranks still complete cycles."""
    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg),
                                secret=SECRET, port=0)

    def one_round():
        outs = {}
        def worker(rank):
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET, rank=rank)
            outs[rank] = client.cycle(
                rank, RequestList(rank=rank,
                                  requests=[_request(rank, "w")]))
            client.close()
        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        return outs

    first = one_round()
    time.sleep(0.5)  # give the liveness monitor a chance to misfire
    second = one_round()  # raises if the close aborted the rendezvous
    service.shutdown()
    assert len(first) == 2 and len(second) == 2


@pytest.mark.parametrize("size", [16])
def test_payload_exchange_correct_at_scale(size):
    """The once-per-cycle framed combine result must still deliver correct
    allreduce bytes to every rank."""
    cfg = Config.from_env()
    service = ControllerService(size, make_negotiator(size, cfg),
                                secret=SECRET, port=0)
    results: dict[int, np.ndarray] = {}
    errors: list[BaseException] = []

    def worker(rank: int) -> None:
        try:
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET)
            rl = RequestList(rank=rank, requests=[_request(rank, "grad")])
            client.cycle(rank, rl)
            payload = np.full(64, float(rank), np.float32)
            raw = client.payload(rank, 0, payload.tobytes())
            results[rank] = np.frombuffer(raw, np.float32)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    service.shutdown()
    assert not errors, errors
    expected = np.full(64, sum(range(size)), np.float32)
    for rank in range(size):
        np.testing.assert_array_equal(results[rank], expected)


def _native_bench_median(size: int, cycles: int = 10) -> tuple:
    import os
    import subprocess
    import sys

    from horovod_tpu import cc

    if not cc.available():
        pytest.skip(f"native core: {cc.load_error()}")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        result = subprocess.run(
            [sys.executable, os.path.join(root, "benchmarks",
                                          "controller_bench.py"),
             "--sizes", str(size), "--impl", "native",
             "--cycles", str(cycles),
             # this test times the MAIN table only; the steady-state cache
             # table has its own coverage (test_response_cache + the bench
             # default) and would spend this subprocess's latency budget
             "--steady-sizes", ""],
            cwd=root, capture_output=True, text=True, timeout=300)
    except subprocess.TimeoutExpired:
        # The bench itself cannot finish inside its budget here — a
        # time-budget limitation of the image, not a controller collapse
        # (a collapse still FINISHES, with terrible medians).
        pytest.skip(f"native controller bench at {size} ranks exceeded "
                    f"its 300s budget on this image")
    assert result.returncode == 0, result.stderr
    # a child-side native-core load failure prints "native skipped: ..."
    # and exits 0 — surface the cause, don't parse it as a data row
    assert "skipped" not in result.stdout, result.stdout
    row = [l for l in result.stdout.splitlines()
           if l.startswith("native ")][0]
    # columns: impl ranks client_med client_worst SERVER_med SERVER_worst
    return float(row.split()[2]), float(row.split()[4])


# One 32-rank calibration run shared by the scale tests below, cached so
# the second test doesn't pay for it again.
_CALIBRATION: dict = {}


def _require_scale_budget(size: int, bound_ms: float) -> None:
    """Skip (with numbers) when this image cannot honor the published
    absolute bounds — without weakening the bench where it CAN run.

    The bounds were measured on hardware where the 32-rank native median
    is ~1-2 ms (9.4 ms epoll at 256 ranks, docs/benchmarks.md). On a
    slow or core-starved CI image the same healthy service measures
    many-fold higher, and the absolute bound then cannot distinguish
    "slow image" from "controller collapse" — the one thing it exists to
    catch. The gate is self-calibrating: run the SAME bench at 32 ranks
    and linearly extrapolate; if that extrapolation alone consumes more
    than half the bound, the bound has no discriminating headroom left
    on this image and the test skips, stating both numbers. On capable
    hardware the calibration costs ~2 s and the full test runs with its
    original bounds."""
    if "median_ms" not in _CALIBRATION:
        _CALIBRATION["median_ms"] = _native_bench_median(32)[0]
    calib = _CALIBRATION["median_ms"]
    extrapolated = calib * (size / 32.0)
    if extrapolated > bound_ms / 2.0:
        pytest.skip(
            f"time budget unavailable on this image: 32-rank native "
            f"median {calib:.1f} ms extrapolates to {extrapolated:.0f} ms "
            f"at {size} ranks, leaving the {bound_ms:.0f} ms bound no "
            f"headroom to tell a slow image from a collapse (healthy "
            f"hardware calibrates at ~1-2 ms)")


def test_controller_bench_native_256_ranks():
    """The scaling-evidence harness (docs/benchmarks.md table) must run and
    the native service must keep 256-rank cycles bounded. Bound is ~10x
    the measured median (9.4 ms epoll on this hardware) to absorb CI
    noise while still catching a collapse; on images too slow to honor
    that absolute bound the calibration gate skips with the numbers."""
    _require_scale_budget(256, 100)
    median_ms, _ = _native_bench_median(256)
    assert median_ms < 100, f"256-rank median cycle {median_ms:.1f} ms"


def test_controller_bench_native_512_ranks():
    """512 ranks — the reference's published coordinator scale
    (``operations.cc:2030``, 5 ms cycles). The epoll event loop measures
    19.9 ms median here with every client GIL-bound on this machine's one
    core; the SERVER column is the service's own active window (4.6 ms
    with worker processes, ~20 ms threaded because GIL-serialized clients
    stretch the arrival spread — docs/benchmarks.md "Direct server-side
    measurement"). Bounds catch a collapse, not a regression to
    thread-per-rank medians; the calibration gate skips slow images."""
    _require_scale_budget(512, 150)
    median_ms, server_ms = _native_bench_median(512)
    assert median_ms < 150, f"512-rank median cycle {median_ms:.1f} ms"
    assert server_ms < 100, (
        f"512-rank SERVER-side median {server_ms:.1f} ms — the epoll "
        f"loop's own active window collapsed")


def test_watch_channel_reconnects_on_transient_drop():
    """The abort-push channel idles for the whole job; a transient
    connection failure must RECONNECT and re-park (a false abort would
    kill a healthy world), and the eventual real abort must be delivered
    through the re-established channel exactly once."""
    from horovod_tpu.runner.network import BasicService

    state = {"watch_requests": 0}
    gate = threading.Event()

    def handle(req, _sock):
        assert req == ("watch", "")  # world id rides the watch wire
        state["watch_requests"] += 1
        if state["watch_requests"] == 1:
            # -> RemoteError -> client-side WireError -> reconnect path
            raise RuntimeError("synthetic transient watch failure")
        gate.wait(timeout=30)
        return ("abort", "rank 1 exited mid-job. shut down")

    svc = BasicService("fake-controller", handle, secret=SECRET, port=0)
    client = ControllerClient(("127.0.0.1", svc.port), secret=SECRET)
    reasons: list[str] = []
    fired = threading.Event()

    def on_abort(reason: str) -> None:
        reasons.append(reason)
        fired.set()

    client.watch(on_abort)
    deadline = time.monotonic() + 20
    while state["watch_requests"] < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert state["watch_requests"] == 2, "watch did not reconnect"
    assert not fired.is_set(), "transient drop must not abort the world"
    gate.set()
    assert fired.wait(10), "abort was not delivered after reconnect"
    assert reasons == ["rank 1 exited mid-job. shut down"]
    svc.shutdown()
    client.close()


def test_watch_channel_clean_stop_fires_nothing():
    """A clean controller stop answers parked watchers with a non-abort
    response; the callback must NOT fire (a spurious abort would race the
    engine's finalizer draining its last batches at shutdown)."""
    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg),
                                secret=SECRET, port=0)
    client = ControllerClient(("127.0.0.1", service.port), secret=SECRET)
    fired = threading.Event()
    client.watch(lambda reason: fired.set())
    time.sleep(0.8)  # let the watch request park
    service.shutdown()
    assert not fired.wait(2.0), "clean stop fired the abort callback"
    # and the watcher must have RETURNED — a parked-forever watcher or one
    # stuck in the reconnect loop would also leave `fired` unset, but
    # those are the hang/spurious-abort regressions this test guards
    _assert_watch_threads_exit()
    client.close()


def _assert_watch_threads_exit(timeout_s: float = 10.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "horovod-abort-watch" and t.is_alive()]
        if not alive:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"watch thread(s) still running after clean stop: {alive}")


def test_hello_retries_through_dying_server_backlog():
    """Re-init race (shutdown(); init() on the same port): a connect can
    land in the DYING previous service's kernel backlog — the kernel
    accepts it, the exiting event loop closes it unserved — so the hello
    gets EOF despite a successful connect. The client must retry the
    connect+hello pair, not give up on the first EOF."""
    import socket

    from horovod_tpu.runner.network import Wire, WireError

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    port = lsock.getsockname()[1]
    wire = Wire(SECRET)
    served = {"conns": 0, "hellos": 0}

    def server() -> None:
        # conn 1: the dying-server backlog victim — closed unserved
        conn, _ = lsock.accept()
        served["conns"] += 1
        conn.close()
        # conn 2: a live service — like the real one, serve requests
        # until the client hangs up. A healed connection carries TWO
        # hellos: the on_reconnect bare re-identify (armed before the
        # initial hello — see connect_with_hello) and then the resent
        # sequenced request.
        conn, _ = lsock.accept()
        served["conns"] += 1
        while True:
            try:
                req = wire.read(conn)
            except (WireError, OSError):
                break  # client closed the healed connection
            if isinstance(req, tuple) and req[0] == "#rpc":
                req = req[3]  # unwrap the dedup envelope (BasicService)
            if req == ("bye", 0):  # clean detach from close()
                conn.sendall(wire.frame(("ok",)))
                continue
            assert req == ("hello", 0, ""), req  # world id rides the hello
            served["hellos"] += 1
            conn.sendall(wire.frame(("ok",)))
        conn.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    client = ControllerClient(("127.0.0.1", port), secret=SECRET, rank=0)
    client.close()
    t.join(timeout=10)
    assert served["conns"] == 2  # first EOF'd, second served the hello
    assert served["hellos"] >= 1  # the retried hello reached the service
    lsock.close()


def test_reconnect_supersedes_old_connection():
    """A second connection identifying as rank R supersedes the first:
    the stale connection's abrupt close (no bye) must NOT be attributed
    as rank R's death — the scenario behind a retried hello whose reply
    was lost. The world must still complete a full cycle afterwards."""
    cfg = Config.from_env()
    service = ControllerService(2, make_negotiator(2, cfg),
                                secret=SECRET, port=0)
    addr = ("127.0.0.1", service.port)
    c1 = ControllerClient(addr, secret=SECRET, rank=0)
    c2 = ControllerClient(addr, secret=SECRET, rank=0)  # supersedes c1
    c1._client.close()  # abrupt: no bye — must be an anonymous close now
    time.sleep(0.5)  # give the disconnect monitor a chance to misfire
    outs = {}
    errors: list[BaseException] = []

    def rank1() -> None:
        try:
            c = ControllerClient(addr, secret=SECRET, rank=1)
            outs[1] = c.cycle(1, RequestList(
                rank=1, requests=[_request(1, "sup.t")]))
            c.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    t = threading.Thread(target=rank1)
    t.start()
    outs[0] = c2.cycle(0, RequestList(rank=0,
                                      requests=[_request(0, "sup.t")]))
    t.join(timeout=30)
    service.shutdown()
    assert not errors, errors
    for out in outs.values():
        assert [n for r in out.responses for n in r.tensor_names] == \
            ["sup.t"]


def test_controller_bench_multiprocess_mode():
    """The round-3 verdict's direct-measurement ask: controller_bench
    --procs spreads clients over real worker processes and reports a
    SERVER-side cycle time drained from the service itself (native:
    htpu_controller_drain_stats; python: the autotune sink). Pin the whole
    path: worker spawn, rank-0 latency relay, server stat drain."""
    import os
    import re
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    result = subprocess.run(
        [sys.executable,
         os.path.join(root, "benchmarks", "controller_bench.py"),
         "--sizes", "8", "--cycles", "6", "--procs", "2",
         "--steady-sizes", ""],  # main-table path only, as above
        cwd=root, env=env, capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr
    rows = [ln for ln in result.stdout.splitlines()
            if re.match(r"(python|native)\s+8\s", ln)]
    assert rows, result.stdout
    for row in rows:
        cols = row.split()
        # impl ranks client_med client_worst server_med server_worst
        assert len(cols) == 6, row
        for v in cols[2:]:
            assert float(v) > 0, row
