"""Native core (C++): autotuner, timeline writer, engine integration.

Reference coverage model: autotuner = parameter_manager/bayesian
optimization behavior (``parameter_manager.cc``), timeline = black-box
artifact check (``test/test_timeline.py``).
"""

import json
import os

import numpy as np
import pytest

import horovod_tpu.cc as cc


pytestmark = pytest.mark.skipif(
    not cc.available(), reason=f"native core unavailable: {cc.load_error()}")


def test_param_manager_tunes_and_tracks_best():
    pm = cc.NativeParameterManager(64 * 1024 * 1024, 5.0)
    changed = False
    # deterministic synthetic workload: bigger fusion windows score higher
    for i in range(200):
        threshold = pm.fusion_threshold_bytes
        score_rate = threshold / (64 * 1024 * 1024)  # bytes per us ∝ window
        moved = pm.update(score_rate * 1e6, 1e6)
        changed = changed or moved
        assert 1024 * 1024 <= pm.fusion_threshold_bytes <= 256 * 1024 * 1024
        assert 0.5 <= pm.cycle_time_ms <= 25.0
    assert changed, "optimizer never moved the knobs"
    best = pm.best
    assert best["score_bytes_per_us"] > 0


def test_param_manager_fixed_knobs_never_move():
    pm = cc.NativeParameterManager(64 * 1024 * 1024, 5.0,
                                   fusion_fixed=True, cycle_fixed=True)
    for _ in range(50):
        assert not pm.update(1e6, 1e6)
    assert pm.fusion_threshold_bytes == 64 * 1024 * 1024
    assert pm.cycle_time_ms == 5.0


def test_native_timeline_writer(tmp_path):
    path = str(tmp_path / "native_timeline.json")
    writer = cc.NativeTimelineWriter(path)
    for i in range(100):
        writer.write(json.dumps({"name": f"ev{i}", "ph": "B", "pid": 0,
                                 "tid": 1, "ts": i * 10.0}))
    writer.close()
    with open(path, encoding="utf-8") as fh:
        records = json.load(fh)
    assert len(records) == 101  # 100 events + closing sentinel
    assert records[0]["name"] == "ev0"


def test_engine_autotune_smoke(tmp_path, monkeypatch):
    """HOROVOD_AUTOTUNE=1 end to end: eager traffic drives the tuner, the
    log file accumulates history, collectives stay correct."""
    log_path = str(tmp_path / "autotune.csv")
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_LOG", log_path)
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")

    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    try:
        rng = np.random.default_rng(7)
        for batch in range(30):
            tensors = [rng.standard_normal(1000).astype(np.float32)
                       for _ in range(8)]
            handles = [hvd.allreduce_async(t, average=False,
                                           name=f"at.{batch}.{i}")
                       for i, t in enumerate(tensors)]
            for t, h in zip(tensors, handles):
                np.testing.assert_array_equal(np.asarray(hvd.synchronize(h)), t)
    finally:
        hvd.shutdown()
    with open(log_path, encoding="utf-8") as fh:
        lines = fh.read().strip().splitlines()
    assert lines[0].startswith("timestamp,fusion_threshold_bytes")
    assert len(lines) > 1, "no autotune samples were logged"
