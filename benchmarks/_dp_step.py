"""Shared data-parallel train-step construction for the benchmark scripts.

One definition of the measured program (model apply + loss + grad +
DistributedOptimizer update + cross-replica BatchNorm averaging, jitted as
a shard_map over the data axis) so `bench.py` and
`benchmarks/scaling_bench.py` cannot drift apart — the reference keeps its
protocol in one script per framework for the same reason
(``examples/pytorch_synthetic_benchmark.py:37-110``).
"""

from __future__ import annotations


def make_dp_train_step(model, opt, mesh, axis_name: str = "data",
                       donate: bool = True, hierarchical=None,
                       scan_batches: int = 1, explicit_grad_reduce=None):
    """Build the jitted DP train step over ``mesh``'s ``axis_name``.

    Returns ``step(params, opt_state, batch_stats, x, y) -> (params,
    opt_state, batch_stats)`` with x/y sharded on the data axis and
    everything else replicated. Models without BatchNorm pass
    ``batch_stats={}`` through unchanged.

    ``scan_batches > 1`` wraps the step body in ``lax.scan`` so ONE
    dispatched call executes N batches back to back on device (same
    static batch — the synthetic-benchmark situation). Diagnostic, not
    protocol: comparing it against N separate dispatches isolates
    Python-dispatch / pipeline-drain overhead from true device time
    (docs/benchmarks.md "Why bs32 caps", item 2).

    ``hierarchical`` (default: follow ``HOROVOD_HIERARCHICAL_ALLREDUCE``
    via the optimizer's own resolution) selects the two-level factored
    gradient reduction over a (dcn, ici) ``axis_name`` pair. That mode
    traces with ``check_vma=False``: under vma tracking shard_map pre-sums
    replicated-param cotangents with a flat whole-mesh psum before the
    optimizer's transform runs, which would silently bypass the factored
    reduce_scatter/psum/all_gather route (``operations.cc:1284-1436``'s
    TPU analog in ``parallel/hierarchical.py``).

    ``explicit_grad_reduce`` (default: equals ``hierarchical``) forces the
    same ``check_vma=False`` tracing WITHOUT the factored route — needed
    whenever the optimizer's own reduction must carry the bytes, e.g.
    gradient compression: under vma tracking the auto-inserted psum runs
    in f32 BEFORE the compress hook, so the cast would be numerics-only
    and never shrink the collective's wire traffic.
    """
    import jax
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if hierarchical is None:
        from horovod_tpu.optimizers import _use_hierarchical

        hierarchical = _use_hierarchical(axis_name, None)
    if explicit_grad_reduce is None:
        explicit_grad_reduce = hierarchical

    def loss_fn(params, batch_stats, x, y):
        logits, updated = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, updated.get("batch_stats", {})

    def train_step(params, opt_state, batch_stats, x, y):
        (_, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, x, y)
        updates, opt_state = opt.update(grads, opt_state, params)
        # cross-replica BN statistics averaging (per-replica stats would be
        # rank-varying; the reference averages metrics the same way)
        new_stats = jax.tree_util.tree_map(
            lambda s: jax.lax.pmean(s, axis_name), new_stats)
        return optax.apply_updates(params, updates), opt_state, new_stats

    if scan_batches > 1:
        single = train_step

        def train_step(params, opt_state, batch_stats, x, y):  # noqa: F811
            def body(carry, _):
                return single(*carry, x, y), None

            carry, _ = jax.lax.scan(body, (params, opt_state, batch_stats),
                                    None, length=scan_batches)
            return carry

    return jax.jit(
        shard_map(train_step, mesh=mesh,
                  in_specs=(P(), P(), P(), P(axis_name), P(axis_name)),
                  out_specs=(P(), P(), P()),
                  check_vma=not (hierarchical or explicit_grad_reduce)),
        donate_argnums=(0, 1, 2) if donate else ())
