#!/usr/bin/env python
"""ZeRO-1 sharding benchmark: per-rank optimizer-state bytes + step time.

The sharding plane's headline claim (docs/sharding.md): partitioning
optimizer state across the world cuts each rank's slot residency to
~1/N of the replicated footprint, while the flush stays ONE compiled
reduce-scatter → apply → all-gather program — so the step-time cost
beside the memory win is visible in the same table. Four cells:

* ``replicated``  — the fused reduce+apply reference (HOROVOD_ZERO=0):
  every rank applies the full tree, slots replicated everywhere.
* ``zero1``       — HOROVOD_ZERO=1: every rank owns one contiguous shard
  of the flattened slots; ``horovod_shard_slot_bytes`` is the residency.

at world sizes 2 and 4 (``--quick`` keeps world 2 only). Adam is the
measured rule — two slot trees, the largest replicated footprint the
plane can halve. Slot residency is read off ONE accounting definition
(``sharding.zero1.resident_bytes`` — the same math behind the
``horovod_shard_slot_bytes`` gauge), not re-derived here. Final line is
the JSON contract ``tools/bench_table.py`` renders::

    python benchmarks/sharding_bench.py            # worlds 2 and 4
    python benchmarks/sharding_bench.py --quick    # world 2, fewer rounds
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

# repo-root import, the benchmarks/ convention (run as a script)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _worker() -> None:
    """Rank body: timed ``hvd.apply_step`` rounds over an Adam tree;
    rank 0 reports wall seconds + this rank's slot residency."""
    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if os.environ.get("SHARDING_BENCH_JAX_COORD"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            os.environ["SHARDING_BENCH_JAX_COORD"],
            num_processes=int(os.environ["HOROVOD_SIZE"]),
            process_id=int(os.environ["HOROVOD_RANK"]))
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import horovod_tpu as hvd
    from horovod_tpu.sharding import zero1 as z1

    n_tensors = int(os.environ["SHARDING_BENCH_TENSORS"])
    n_elems = int(os.environ["SHARDING_BENCH_ELEMS"])
    rounds = int(os.environ["SHARDING_BENCH_ROUNDS"])
    hvd.init()

    tx = hvd.DistributedOptimizer(hvd.fused_adam(1e-3))
    params = {f"t{i}": np.full((n_elems,), 0.5, np.float32)
              for i in range(n_tensors)}
    opt_state = tx.init(params)
    # deterministic per-rank gradients, so replicated and zero1 runs
    # reduce identical sums and the step loop does identical math
    grads = {f"t{i}": np.full((n_elems,), 0.001 * (i + 1)
                              * (hvd.rank() + 1), np.float32)
             for i in range(n_tensors)}

    def one_round() -> None:
        nonlocal params, opt_state
        params, opt_state = hvd.apply_step(tx, grads, opt_state, params)

    one_round()  # warm the compile cache / connections
    one_round()
    t0 = time.perf_counter()
    for _ in range(rounds):
        one_round()
    dt = time.perf_counter() - t0

    # Residency off the one accounting definition the gauge uses: shard
    # leaves count their shard only, replicated leaves their full size.
    slot_bytes = z1.resident_bytes(opt_state.inner.slots)
    param_bytes = n_tensors * n_elems * 4
    from horovod_tpu.ops.engine import get_engine

    ap = get_engine().apply_stats()
    if hvd.rank() == 0:
        print(json.dumps({
            "seconds": dt,
            "steps_per_s": rounds / dt,
            "slot_bytes": slot_bytes,
            "param_bytes": param_bytes,
            "zero1_batches": ap.get("zero1_batches", 0),
            "exec_zero1": bool(ap.get("exec_zero1")),
        }), flush=True)
    hvd.shutdown()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(world: int, zero1: bool, args) -> dict:
    port = _free_port()
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": str(world),
            "HOROVOD_LOCAL_RANK": str(rank),
            "HOROVOD_LOCAL_SIZE": str(world),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HOROVOD_DATA_PLANE": "xla",
            "HOROVOD_CYCLE_TIME": "1",
            "HOROVOD_FUSED_APPLY": "1",
            "HOROVOD_ZERO": "1" if zero1 else "0",
            "SHARDING_BENCH_WORKER": "1",
            "SHARDING_BENCH_TENSORS": str(args.tensors),
            "SHARDING_BENCH_ELEMS": str(args.elems),
            "SHARDING_BENCH_ROUNDS": str(args.rounds),
            "SHARDING_BENCH_JAX_COORD": coord,
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(f"worker failed:\n{err}")
    return json.loads(outs[0][0].strip().splitlines()[-1])


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - sha is cosmetic
        return "unknown"


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tensors", type=int, default=16,
                        help="parameter leaves (Adam: 2 slot trees)")
    parser.add_argument("--elems", type=int, default=65_536,
                        help="float32 elements per leaf (~256 KB)")
    parser.add_argument("--rounds", type=int, default=8)
    parser.add_argument("--quick", action="store_true",
                        help="world 2 only, fewer rounds")
    args = parser.parse_args()
    if args.quick:
        args.rounds = min(args.rounds, 4)

    mb = args.tensors * args.elems * 4 / 1e6
    worlds = (2,) if args.quick else (2, 4)
    print(f"# sharding benchmark: {args.tensors} x "
          f"{args.elems * 4 / 1e3:.0f} KB Adam leaves ({mb:.1f} MB "
          f"params, {2 * mb:.1f} MB replicated slots), "
          f"{args.rounds} rounds")
    print(f"{'world':>5} {'mode':<11} {'steps/s':>8} {'slot MB/rank':>13} "
          f"{'vs replicated':>14}")
    cells = []
    for world in worlds:
        base_bytes = None
        for zero1 in (False, True):
            r = _run_world(world, zero1, args)
            mode = "zero1" if zero1 else "replicated"
            if base_bytes is None:
                base_bytes = r["slot_bytes"]
            frac = r["slot_bytes"] / base_bytes if base_bytes else 0.0
            print(f"{world:>5} {mode:<11} {r['steps_per_s']:>8.2f} "
                  f"{r['slot_bytes'] / 1e6:>13.2f} {frac:>13.2%}")
            cells.append({"world": world, "mode": mode,
                          "steps_per_s": round(r["steps_per_s"], 3),
                          "slot_bytes": r["slot_bytes"],
                          "slot_fraction": round(frac, 4),
                          "zero1_batches": r["zero1_batches"],
                          "exec_zero1": r["exec_zero1"]})
    print("BENCH " + json.dumps({
        "bench": "sharding", "git": _git_sha(),
        "tensors": args.tensors, "elems": args.elems,
        "rounds": args.rounds, "cells": cells}))


if __name__ == "__main__":
    if os.environ.get("SHARDING_BENCH_WORKER"):
        _worker()
    else:
        main()
