#!/usr/bin/env python
"""Controller cycle-latency vs world size, for both controller backends.

The reference's coordinator holds 5 ms negotiation cycles at 512 MPI ranks
(``operations.cc:2030``). Two measurement modes:

* default (threads): N GIL-bound client threads in this process — a
  pessimistic harness whose client-side numbers include the GIL-serialized
  encode of all N clients.
* ``--procs W``: N ranks spread over W real worker processes (the round-3
  verdict's ask — de-GILs the client encode so the server is measured
  under genuinely parallel load), e.g. ``--sizes 512 --procs 8`` runs
  8 x 64 clients.

In both modes the table now carries a SERVER-side column measured inside
the service itself (first rank's cycle request -> response broadcast
queued, the native server's autotune stat and its Python-service twin) —
a direct cycle-time measurement needing no harness-floor subtraction.

Produces the table in docs/benchmarks.md:

    python benchmarks/controller_bench.py                 # both backends
    python benchmarks/controller_bench.py --sizes 128,512 --procs 8
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.core.config import Config
from horovod_tpu.ops.controller import (
    ControllerClient,
    ControllerService,
    Negotiator,
    make_negotiator,
)
from horovod_tpu.ops.messages import (
    CacheHitAck,
    CacheRequest,
    DataType,
    Request,
    RequestList,
    RequestType,
)
from horovod_tpu.ops.response_cache import ResponseCache, bits_of

SECRET = b"s" * 32


class _StatSink:
    """Autotuner stand-in that only records the service's own per-cycle
    active time (µs); never retunes."""

    def __init__(self) -> None:
        self.us: list[float] = []

    def observe_cycle(self, response_list, active_us=None):
        if active_us is not None:
            self.us.append(active_us)
        return None


def _request(rank: int, name: str) -> Request:
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=(64,), root_rank=-1)


def _client_cls(impl: str):
    if impl == "native":
        from horovod_tpu.ops.native_controller import NativeControllerClient

        return NativeControllerClient
    return ControllerClient


def _make_service(impl: str, size: int):
    """Service plus a () -> list[us] drain of its server-side cycle stats."""
    cfg = Config.from_env()
    if impl == "native":
        from horovod_tpu.ops.native_controller import NativeControllerService

        service = NativeControllerService(size, cfg, secret=SECRET, port=0,
                                          collect_stats=True)
        return service, lambda: [us for _, us in service.drain_stats()]
    sink = _StatSink()
    service = ControllerService(size, make_negotiator(size, cfg),
                                secret=SECRET, port=0, autotuner=sink)
    return service, lambda: list(sink.us)


def _run_clients(impl: str, port: int, ranks, n_cycles: int,
                 tensors_per_cycle: int, barrier=None,
                 record_rank: int = 0) -> list[float]:
    """Drive ``ranks`` as threads against an existing service; returns
    client-side latencies observed by ``record_rank`` (if driven here)."""
    client_cls = _client_cls(impl)
    latencies: list[float] = []
    errors: list[BaseException] = []

    def worker(rank: int) -> None:
        try:
            client = client_cls(("127.0.0.1", port), secret=SECRET,
                                rank=rank)
            for c in range(n_cycles):
                requests = [_request(rank, f"t{c}_{i}")
                            for i in range(tensors_per_cycle)]
                if barrier is not None:
                    barrier.wait(timeout=120)
                t0 = time.perf_counter()
                client.cycle(rank, RequestList(rank=rank, requests=requests))
                if rank == record_rank:
                    latencies.append(time.perf_counter() - t0)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)
            if barrier is not None:
                barrier.abort()

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in ranks]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    hung = sum(1 for t in threads if t.is_alive())
    if errors:
        raise RuntimeError(f"{impl} clients failed: {errors[:3]}")
    if hung:
        # a rank blocked inside cycle() IS the collapse this harness
        # exists to catch — never report partial latencies as healthy
        raise RuntimeError(f"{impl}: {hung} client(s) hung past the join "
                           f"timeout; no valid measurement")
    return latencies


def _measure(impl: str, size: int, n_cycles: int, tensors_per_cycle: int,
             procs: int = 0):
    """Returns (client_median_s, client_worst_s, server_median_s,
    server_worst_s). Client side is rank 0's blocking cycle() time; server
    side is the service's own active window."""
    service, drain = _make_service(impl, size)
    try:
        if procs <= 1:
            # all ranks enter each cycle together so the client latency is
            # the full gather+construct+broadcast rendezvous, not
            # thread-start skew
            barrier = threading.Barrier(size)
            latencies = _run_clients(impl, service.port, range(size),
                                     n_cycles, tensors_per_cycle,
                                     barrier=barrier)
        else:
            if size % procs:
                raise ValueError(f"size {size} not divisible by {procs}")
            per = size // procs
            worker_argv = [
                [sys.executable, os.path.abspath(__file__), "--_worker",
                 "--impl", impl, "--port", str(service.port),
                 "--base-rank", str(p * per), "--n-ranks", str(per),
                 "--cycles", str(n_cycles),
                 "--tensors-per-cycle", str(tensors_per_cycle)]
                for p in range(procs)
            ]
            children = [subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True) for argv in worker_argv]
            outs = []
            for child in children:
                try:
                    out, err = child.communicate(timeout=600)
                except subprocess.TimeoutExpired:
                    for c in children:
                        c.kill()
                    raise RuntimeError(
                        f"{impl} @ {size}: worker process hung")
                if child.returncode != 0:
                    for c in children:
                        c.kill()
                    raise RuntimeError(
                        f"{impl} @ {size}: worker failed:\n{err[-2000:]}")
                outs.append(out)
            # rank 0 lives in worker 0; scan its stdout in reverse for the
            # JSON latency list — a library banner or interpreter-shutdown
            # warning printed after the json.dumps must not break the parse
            # (shared tolerant parse with bench.py's supervisor).
            from horovod_tpu.core.provenance import last_json_line

            _, latencies = last_json_line(outs[0], want=list)
            if latencies is None:
                raise RuntimeError(
                    f"{impl} @ {size}: no JSON latency list in worker 0 "
                    f"stdout:\n{outs[0][-2000:]}")
        server_us = drain()
    finally:
        service.shutdown()
    # first cycle carries connect+auth for every rank; drop it
    timed = latencies[1:] or latencies
    s_timed = [u / 1e6 for u in (server_us[1:] or server_us)]
    return (statistics.median(timed), max(timed),
            statistics.median(s_timed) if s_timed else float("nan"),
            max(s_timed) if s_timed else float("nan"))


def _make_core(core: str, size: int, cfg):
    """A negotiation core by explicit choice (the steady-state table
    compares BOTH cores under one Python controller service; the response
    cache wraps whichever core runs — docs/response-cache.md)."""
    if core == "native":
        from horovod_tpu import cc

        return cc.NativeNegotiator(size, cfg.fusion_threshold_bytes,
                                   stall_warning_s=cfg.stall_warning_time_s)
    return Negotiator(size, cfg.fusion_threshold_bytes,
                      stall_warning_s=cfg.stall_warning_time_s)


def _steady_measure(core: str, size: int, n_cycles: int,
                    tensors_per_cycle: int, cache_capacity: int):
    """Steady-state training shape: every rank submits the SAME tensor set
    every cycle (the pattern the response cache exists for). Returns
    (cycles_per_s, neg_bytes_per_cycle) over the warm portion (first two
    cycles dropped: connect/auth + the populating miss)."""
    cfg = Config.from_env()
    service = ControllerService(
        size, _make_core(core, size, cfg), secret=SECRET, port=0,
        cache_capacity=cache_capacity,
        fusion_threshold_bytes=cfg.fusion_threshold_bytes)
    latencies: list[float] = []
    nbytes: list[int] = []
    errors: list[BaseException] = []
    barrier = threading.Barrier(size)

    def worker(rank: int) -> None:
        try:
            client = ControllerClient(("127.0.0.1", service.port),
                                      secret=SECRET, rank=rank)
            cache = ResponseCache(cache_capacity)
            requests = [_request(rank, f"steady_{i}")
                        for i in range(tensors_per_cycle)]
            by_name = {r.tensor_name: r for r in requests}
            for _ in range(n_cycles):
                positions = cache.plan_cycle(requests) \
                    if cache_capacity > 0 else None
                barrier.wait(timeout=120)
                t0 = time.perf_counter()
                if positions is not None:
                    out = client.cycle(rank, CacheRequest(
                        rank=rank,
                        bits=bits_of(positions, cache.capacity),
                        generation=cache.generation))
                else:
                    out = client.cycle(rank, RequestList(
                        rank=rank, requests=list(requests)))
                dt = time.perf_counter() - t0
                if isinstance(out, CacheHitAck):
                    replayed = cache.accept_ack(out)
                    assert len(replayed) >= 1
                else:
                    cache.accept_response_list(out, by_name)
                if rank == 0:
                    latencies.append(dt)
                    nbytes.append(client.last_cycle_tx_bytes
                                  + client.last_cycle_rx_bytes)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)
            barrier.abort()

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    service.shutdown()
    if errors:
        raise RuntimeError(f"steady {core} clients failed: {errors[:3]}")
    if any(t.is_alive() for t in threads):
        raise RuntimeError(f"steady {core}: client hung; no measurement")
    warm_lat, warm_bytes = latencies[2:], nbytes[2:]
    return (1.0 / statistics.median(warm_lat),
            statistics.median(warm_bytes))


def steady_state_table(cores, sizes, n_cycles: int,
                       tensors_per_cycle: int) -> None:
    """The acceptance table: warm-cache steady state must send strictly
    fewer negotiation bytes/cycle than cold (bitvector + ack vs. full
    RequestList/ResponseList) and turn that into a cycles/sec speedup, on
    both negotiation cores."""
    print(f"\n# steady-state negotiation bypass (HOROVOD_CACHE_CAPACITY), "
          f"{tensors_per_cycle} tensors/cycle, {n_cycles} cycles, "
          f"Python controller service, threaded clients")
    # "-core" suffix: these rows compare NEGOTIATION CORES under the one
    # Python service, and must not parse as the main table's impl rows
    # (test_controller_scale greps those by leading "python "/"native ")
    print(f"{'core':<12} {'ranks':>6} {'cold cyc/s':>11} {'warm cyc/s':>11} "
          f"{'speedup':>8} {'cold B/cyc':>11} {'warm B/cyc':>11}")
    for core in cores:
        for size in sizes:
            cold_cps, cold_b = _steady_measure(core, size, n_cycles,
                                               tensors_per_cycle, 0)
            warm_cps, warm_b = _steady_measure(core, size, n_cycles,
                                               tensors_per_cycle, 1024)
            print(f"{core + '-core':<12} {size:>6} {cold_cps:>11.0f} "
                  f"{warm_cps:>11.0f} {warm_cps / cold_cps:>7.2f}x "
                  f"{cold_b:>11.0f} {warm_b:>11.0f}", flush=True)


def _scaling_row(size: int, n_islands: int, n_cycles: int,
                 tensors_per_cycle: int) -> dict:
    """One simulated-world scaling row (docs/hierarchy.md): no sockets —
    at 10^4 ranks the interesting quantities are what the ROOT must
    absorb per cycle, and those are computable from the real message
    pipeline. Flat: every rank's framed ``cycle`` RPC lands on the root.
    Tree: each island's members land on their head, the head merges, and
    the root absorbs ONE framed ``island_cycle`` per island. Bytes are
    the actual wire framing (HMAC + length + pickle) of the actual
    messages; cycles/sec times a real Negotiator fed the per-rank lists
    (flat) vs fed the root-side expansions of the merged submissions
    (tree) — the same compute the live root runs."""
    from horovod_tpu.ops.hierarchy import merge_cycle, plan_topology
    from horovod_tpu.ops.hierarchy import expand_submission
    from horovod_tpu.runner.network import Wire

    cfg = Config.from_env()
    hier = plan_topology(size, f"islands:{n_islands}")
    assert not hier.flat, (size, n_islands)
    wire = Wire(SECRET)
    lists = {
        r: RequestList(rank=r, requests=[
            _request(r, f"t{i}") for i in range(tensors_per_cycle)])
        for r in range(size)
    }
    flat_bytes = sum(len(wire.frame(("cycle", r, lists[r])))
                     for r in range(size))
    subs = {i: merge_cycle(i, members,
                           {r: lists[r] for r in members})
            for i, members in hier.islands.items()}
    assert all(s.raw is None for s in subs.values()), \
        "symmetric workload must merge on every island"
    tree_bytes = sum(
        len(wire.frame(("island_cycle", min(members), i, subs[i])))
        for i, members in hier.islands.items())

    def cycles_per_s(feed) -> float:
        neg = make_negotiator(size, cfg)
        t0 = time.perf_counter()
        for _ in range(n_cycles):
            slot = feed()
            for r in range(size):
                neg.add_request_list(slot[r])
            neg.construct_response_list()
        return n_cycles / (time.perf_counter() - t0)

    def tree_feed():
        slot = {}
        for sub in subs.values():
            slot.update(expand_submission(sub))
        return slot

    return {"ranks": size, "islands": hier.n_islands,
            "flat_root_msgs": size,
            "tree_root_msgs": hier.n_islands,
            "flat_root_bytes": flat_bytes,
            "tree_root_bytes": tree_bytes,
            "flat_cycles_per_s": round(cycles_per_s(lambda: lists), 2),
            "tree_cycles_per_s": round(cycles_per_s(tree_feed), 2)}


def scaling_table(sizes, n_cycles: int, tensors_per_cycle: int) -> None:
    """The tentpole's acceptance table: root messages and bytes per cycle
    must grow ~O(islands), not O(ranks), from 10^2 to 10^4 simulated
    ranks. The last stdout line is the capture JSON
    (``tools/bench_table.py`` renders it; the repo's tool contract)."""
    import math

    print(f"\n# negotiation-tree root load, simulated worlds, "
          f"{tensors_per_cycle} tensors/cycle (cold RequestList shape), "
          f"islands = floor(sqrt(ranks))")
    print(f"{'ranks':>7} {'islands':>8} {'flat msgs/cyc':>14} "
          f"{'tree msgs/cyc':>14} {'flat B/cyc':>12} {'tree B/cyc':>12} "
          f"{'flat cyc/s':>11} {'tree cyc/s':>11}")
    rows = []
    for size in sizes:
        row = _scaling_row(size, max(2, math.isqrt(size)), n_cycles,
                           tensors_per_cycle)
        rows.append(row)
        print(f"{row['ranks']:>7} {row['islands']:>8} "
              f"{row['flat_root_msgs']:>14} {row['tree_root_msgs']:>14} "
              f"{row['flat_root_bytes']:>12} {row['tree_root_bytes']:>12} "
              f"{row['flat_cycles_per_s']:>11.1f} "
              f"{row['tree_cycles_per_s']:>11.1f}", flush=True)
    last = rows[-1]
    print(json.dumps({
        "metric": "hier_root_message_reduction",
        "value": round(last["flat_root_msgs"] / last["tree_root_msgs"],
                       1),
        "unit": "x",
        "ranks": last["ranks"],
        "hierarchy": {"rows": rows,
                      "tensors_per_cycle": tensors_per_cycle}}),
        flush=True)


def _worker_main(args) -> None:
    ranks = range(args.base_rank, args.base_rank + args.n_ranks)
    # Free-running (no cross-process barrier): the controller's own
    # rendezvous paces every rank after cycle 0, so the server-side active
    # window captures the true operational arrival spread.
    latencies = _run_clients(args.impl, args.port, ranks, args.cycles,
                             args.tensors_per_cycle, barrier=None,
                             record_rank=0)
    print(json.dumps(latencies), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sizes", default="8,16,32,64,128",
                        help="comma-separated world sizes")
    parser.add_argument("--impl", default="both",
                        choices=["python", "native", "both"])
    parser.add_argument("--cycles", type=int, default=20)
    parser.add_argument("--tensors-per-cycle", type=int, default=8)
    parser.add_argument("--procs", type=int, default=0,
                        help="spread clients over this many worker "
                             "PROCESSES (0 = threads in-process)")
    parser.add_argument("--steady-sizes", default="8",
                        help="world sizes for the steady-state cache table "
                             "(empty string skips it; keep the default "
                             "small — the main-table scale tests budget "
                             "their subprocess timeout around it)")
    parser.add_argument("--steady-cycles", type=int, default=30)
    parser.add_argument("--scaling", action="store_true",
                        help="run ONLY the negotiation-tree root-load "
                             "scaling table over simulated worlds "
                             "(docs/hierarchy.md) — no sockets, so "
                             "10^4-rank rows are cheap")
    parser.add_argument("--scaling-sizes", default="100,1000,10000",
                        help="simulated world sizes for --scaling")
    parser.add_argument("--scaling-cycles", type=int, default=3,
                        help="negotiation cycles timed per --scaling row")
    # internal worker mode
    parser.add_argument("--_worker", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--port", type=int, help=argparse.SUPPRESS)
    parser.add_argument("--base-rank", type=int, help=argparse.SUPPRESS)
    parser.add_argument("--n-ranks", type=int, help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args._worker:
        _worker_main(args)
        return

    if args.scaling:
        scaling_table([int(s) for s in args.scaling_sizes.split(",")],
                      args.scaling_cycles, args.tensors_per_cycle)
        return

    impls = ["python", "native"] if args.impl == "both" else [args.impl]
    sizes = [int(s) for s in args.sizes.split(",")]
    mode = (f"{args.procs} worker processes" if args.procs > 1
            else "GIL-bound threaded clients")
    print(f"# controller cycle latency, {args.tensors_per_cycle} tensors/"
          f"cycle, {args.cycles} cycles, {mode}")
    print(f"{'impl':<8} {'ranks':>6} {'client med ms':>14} "
          f"{'client worst':>13} {'SERVER med ms':>14} {'SERVER worst':>13}")
    for impl in impls:
        if impl == "native":
            from horovod_tpu import cc

            if not cc.available():
                print(f"native   skipped: {cc.load_error()}")
                continue
        for size in sizes:
            cm, cw, sm, sw = _measure(impl, size, args.cycles,
                                      args.tensors_per_cycle,
                                      procs=args.procs)
            print(f"{impl:<8} {size:>6} {cm * 1e3:>14.1f} {cw * 1e3:>13.1f} "
                  f"{sm * 1e3:>14.2f} {sw * 1e3:>13.2f}", flush=True)

    if args.steady_sizes.strip():
        from horovod_tpu import cc

        cores = ["python"] + (["native"] if cc.available() else [])
        if len(cores) == 1:
            print(f"steady: native core skipped: {cc.load_error()}")
        steady_state_table(cores,
                           [int(s) for s in args.steady_sizes.split(",")],
                           args.steady_cycles, args.tensors_per_cycle)


if __name__ == "__main__":
    main()
