#!/usr/bin/env python
"""Controller cycle-latency vs world size, for both controller backends.

The reference's coordinator holds 5 ms negotiation cycles at 512 MPI ranks
(``operations.cc:2030``). This environment cannot host 512 processes, so
the harness drives N GIL-bound client threads against one service in this
process — a pessimistic stand-in that still exercises the coordinator-side
serial work that collapses first (accept backlog, rendezvous wakeups,
response serialization). Real distributed clients see lower numbers than
this harness reports.

Produces the table in docs/benchmarks.md:

    python benchmarks/controller_bench.py                 # both backends
    python benchmarks/controller_bench.py --sizes 8,64,256 --impl native
"""

from __future__ import annotations

import argparse
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.core.config import Config
from horovod_tpu.ops.controller import (
    ControllerClient,
    ControllerService,
    make_negotiator,
)
from horovod_tpu.ops.messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
)

SECRET = b"s" * 32


def _request(rank: int, name: str) -> Request:
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=(64,), root_rank=-1)


def _measure(impl: str, size: int, n_cycles: int,
             tensors_per_cycle: int) -> tuple[float, float]:
    """Median and worst rank-0 cycle latency (seconds)."""
    cfg = Config.from_env()
    if impl == "native":
        from horovod_tpu.ops.native_controller import (
            NativeControllerClient,
            NativeControllerService,
        )

        service = NativeControllerService(size, cfg, secret=SECRET, port=0)
        client_cls = NativeControllerClient
    else:
        service = ControllerService(size, make_negotiator(size, cfg),
                                    secret=SECRET, port=0)
        client_cls = ControllerClient
    latencies: list[float] = []
    errors: list[BaseException] = []
    # all ranks enter each cycle together so the measured latency is the
    # full gather+construct+broadcast rendezvous, not thread-start skew
    barrier = threading.Barrier(size)

    def worker(rank: int) -> None:
        try:
            client = client_cls(("127.0.0.1", service.port), secret=SECRET,
                                rank=rank)
            for c in range(n_cycles):
                requests = [_request(rank, f"t{c}_{i}")
                            for i in range(tensors_per_cycle)]
                barrier.wait(timeout=120)
                t0 = time.perf_counter()
                client.cycle(rank, RequestList(rank=rank, requests=requests))
                if rank == 0:
                    latencies.append(time.perf_counter() - t0)
            client.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)
            # release peers blocked on the barrier — one failed rank must
            # fail the run, not hang it (threads are daemon anyway, but the
            # abort turns a silent 600 s join timeout into the real error)
            barrier.abort()

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    hung = sum(1 for t in threads if t.is_alive())
    service.shutdown()
    if errors:
        raise RuntimeError(f"{impl} @ {size} ranks failed: {errors[:3]}")
    if hung:
        # a rank blocked inside cycle() IS the collapse this harness
        # exists to catch — never report partial latencies as a healthy
        # measurement
        raise RuntimeError(
            f"{impl} @ {size} ranks: {hung} rank(s) hung past the join "
            f"timeout; no valid measurement")
    # first cycle carries connect+auth for every rank; drop it
    timed = latencies[1:] or latencies
    return statistics.median(timed), max(timed)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sizes", default="8,16,32,64,128",
                        help="comma-separated world sizes")
    parser.add_argument("--impl", default="both",
                        choices=["python", "native", "both"])
    parser.add_argument("--cycles", type=int, default=20)
    parser.add_argument("--tensors-per-cycle", type=int, default=8)
    args = parser.parse_args()

    impls = ["python", "native"] if args.impl == "both" else [args.impl]
    sizes = [int(s) for s in args.sizes.split(",")]
    print(f"# controller cycle latency, {args.tensors_per_cycle} tensors/"
          f"cycle, {args.cycles} cycles, GIL-bound threaded clients")
    print(f"{'impl':<8} {'ranks':>6} {'median ms':>10} {'worst ms':>10}")
    for impl in impls:
        if impl == "native":
            from horovod_tpu import cc

            if not cc.available():
                print(f"native   skipped: {cc.load_error()}")
                continue
        for size in sizes:
            median, worst = _measure(impl, size, args.cycles,
                                     args.tensors_per_cycle)
            print(f"{impl:<8} {size:>6} {median * 1e3:>10.1f} "
                  f"{worst * 1e3:>10.1f}", flush=True)


if __name__ == "__main__":
    main()
