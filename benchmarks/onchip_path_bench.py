#!/usr/bin/env python
"""Single-chip measurement of the device-resident eager path's claim.

The eager engine keeps ``jax.Array`` submissions on-device through the
fusion buffer (``ops/xla_plane.py`` ``allreduce_onchip``: jitted pack →
bucketed psum → jitted unpack), the TPU analog of reference tensors
staying on-GPU through the NCCL fusion buffer
(``operations.cc:1115-1208``). The claim is that this beats staging the
batch through host memory (per-entry D2H, host pack, H2D, collective,
D2H, per-entry H2D back) — which is what the host-fed path costs a rank
whose tensors live on an accelerator.

A multi-process device-plane world cannot run on this environment's ONE
real chip (one process per rank owns the chip), so this bench isolates
exactly the staging difference on a single device: both paths run the
same bucketed psum program over a 1-device mesh through the same
``XlaDataPlane`` code; only the residency of the pack/unpack differs.
Isolated this way the on-chip path wins even on CPU (~1.9x measured,
docs/benchmarks.md) — the slower CPU number in fusion_bench's 2-process
jax-input row comes from per-cycle negotiation, not from this staging
path. On a real accelerator the avoided transfers cross PCIe, where the
claim has teeth.

Usage: python benchmarks/onchip_path_bench.py [--tensors 64]
           [--elems 25000] [--rounds 20]
Prints one JSON line: {"platform", "host_tensors_per_s",
"onchip_tensors_per_s", "onchip_speedup", "captured_at", "git_sha"}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tensors", type=int, default=64)
    parser.add_argument("--elems", type=int, default=25_000)
    parser.add_argument("--rounds", type=int, default=20)
    args = parser.parse_args()

    import jax

    pin = os.environ.get("HOROVOD_BENCH_PLATFORM")
    if pin:
        jax.config.update("jax_platforms", pin)
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.ops.xla_plane import XlaDataPlane

    class _Topo:
        rank = 0
        size = 1

    plane = XlaDataPlane(_Topo())
    platform = jax.devices()[0].platform
    tensors = [jnp.full((args.elems,), float(i), jnp.float32)
               for i in range(args.tensors)]
    jax.block_until_ready(tensors)
    shapes = [t.shape for t in tensors]

    def host_path() -> None:
        # the host-fed fused batch for device-resident inputs: D2H every
        # entry, one host pack, then the shared collective (H2D + psum +
        # D2H inside plane.allreduce), then per-entry H2D back
        buf = np.concatenate([np.asarray(t).ravel() for t in tensors])
        out = plane.allreduce(buf)
        outs, off = [], 0
        for shape in shapes:
            n = int(np.prod(shape))
            outs.append(jax.device_put(out[off:off + n].reshape(shape)))
            off += n
        jax.block_until_ready(outs)

    def onchip_path() -> None:
        jax.block_until_ready(plane.allreduce_onchip(tensors))

    def measure(fn) -> float:
        fn()  # warm the compile caches
        fn()
        t0 = time.perf_counter()
        for _ in range(args.rounds):
            fn()
        dt = time.perf_counter() - t0
        return args.rounds * args.tensors / dt

    host_rate = measure(host_path)
    onchip_rate = measure(onchip_path)
    from horovod_tpu.core.provenance import git_head_sha

    print(json.dumps({
        "platform": platform,
        "host_tensors_per_s": round(host_rate, 1),
        "onchip_tensors_per_s": round(onchip_rate, 1),
        "onchip_speedup": round(onchip_rate / host_rate, 2),
        "captured_at": round(time.time(), 1),
        "git_sha": git_head_sha(os.path.dirname(os.path.abspath(__file__))),
    }))


if __name__ == "__main__":
    main()
