#!/usr/bin/env python
"""Scaling-curve harness: DP throughput and efficiency vs device count.

The reference's headline result is a scaling chart — img/sec at 1..512
GPUs with ~90% efficiency for ResNet-101/Inception V3
(``docs/benchmarks.md:5-6`` there); BASELINE.md's north star for this
build is the same curve on a TPU pod (>=90% at v5e-256). This harness
produces that curve for whatever devices are visible:

* on a TPU pod slice: real chips over ICI — the production measurement;
* on this dev box: N virtual CPU XLA devices — validates the harness and
  the sharded step end-to-end (CPU img/s is NOT a TPU prediction).

Each device count runs in a fresh subprocess (XLA device count is fixed at
backend init). Per point: the same global batch PER DEVICE (weak scaling,
the reference's protocol), mean img/s over timed iters, efficiency =
(img/s at n) / (n * img/s at 1).

Usage: python benchmarks/scaling_bench.py [--devices 1,2,4,8]
         [--model tiny|resnet50] [--platform cpu|native]
         [--batch-size 32] [--iters 5] [--batches-per-iter 3]
Prints one JSON line per point and a final efficiency table.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def _measure() -> None:
    """Subprocess body: one scaling point on n virtual/real devices."""
    n = int(os.environ["SCALING_N_DEVICES"])
    platform = os.environ["SCALING_PLATFORM"]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if platform == "cpu":
        from horovod_tpu.core.platform import pin_cpu_platform

        pin_cpu_platform(n)
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    import horovod_tpu as hvd
    from benchmarks._dp_step import make_dp_train_step

    model_name = os.environ["SCALING_MODEL"]
    batch = int(os.environ["SCALING_BATCH"])
    iters = int(os.environ["SCALING_ITERS"])
    bpi = int(os.environ["SCALING_BPI"])

    hvd.init()
    available = jax.devices()
    if len(available) < n:
        raise RuntimeError(
            f"scaling point n={n} requested but only {len(available)} "
            f"{available[0].platform} device(s) are visible — the point "
            f"would silently measure a smaller mesh.")
    devices = available[:n]
    mesh = Mesh(np.asarray(devices), ("data",))

    if model_name == "resnet50":
        from horovod_tpu.models import ResNet50

        model, side, num_classes = ResNet50(num_classes=1000), 224, 1000
    else:  # tiny: harness validation on CPU in seconds, same code path
        from horovod_tpu.models import ResNet
        from horovod_tpu.models.resnet import ResNetBlock

        model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10,
                       block_cls=ResNetBlock, dtype=jnp.float32)
        side, num_classes = 32, 10

    global_batch = batch * n
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (global_batch, side, side, 3), jnp.float32)
    # label range follows the model's class count so this script measures
    # the identical protocol as bench.py (labels 0..999 for resnet50)
    y = jax.random.randint(rng, (global_batch,), 0, num_classes)
    variables = model.init(jax.random.PRNGKey(1), x[:2])
    params, batch_stats = variables["params"], variables.get(
        "batch_stats", {})
    opt = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name="data")
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    step = make_dp_train_step(model, opt, mesh, axis_name="data")

    for _ in range(2):  # warmup / compile
        params, opt_state, batch_stats = step(params, opt_state,
                                              batch_stats, x, y)
    jax.block_until_ready(params)
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(bpi):
            params, opt_state, batch_stats = step(params, opt_state,
                                                  batch_stats, x, y)
        jax.block_until_ready(params)
        rates.append(global_batch * bpi / (time.perf_counter() - t0))
    print(json.dumps({"devices": n, "img_per_s": float(np.mean(rates))}))
    hvd.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--devices", default="1,2,4,8",
                        help="comma list of device counts to measure")
    parser.add_argument("--model", default="tiny",
                        choices=["tiny", "resnet50"])
    parser.add_argument("--platform", default="cpu",
                        choices=["cpu", "native"],
                        help="cpu = virtual XLA CPU devices (harness "
                             "validation); native = whatever jax.devices() "
                             "exposes (the pod measurement)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--batches-per-iter", type=int, default=3)
    args = parser.parse_args()

    counts = [int(c) for c in args.devices.split(",")]
    points = []
    for n in counts:
        env = dict(os.environ)
        env.update({
            "SCALING_WORKER": "1",
            "SCALING_N_DEVICES": str(n),
            "SCALING_PLATFORM": args.platform,
            "SCALING_MODEL": args.model,
            "SCALING_BATCH": str(args.batch_size),
            "SCALING_ITERS": str(args.iters),
            "SCALING_BPI": str(args.batches_per_iter),
        })
        if args.platform == "cpu":
            env.pop("JAX_PLATFORMS", None)
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=1800)
        if out.returncode != 0:
            raise RuntimeError(f"point n={n} failed:\n{out.stderr}")
        points.append(json.loads(out.stdout.strip().splitlines()[-1]))
        print(json.dumps(points[-1]), flush=True)

    # Efficiency is defined against the single-device point (BASELINE.md's
    # ">=90% at 256 chips" is relative to n=1); without one, fall back to
    # the smallest measured point and say so.
    one = next((p for p in points if p["devices"] == 1), None)
    ref = one or min(points, key=lambda p: p["devices"])
    base = ref["img_per_s"] / ref["devices"]
    suffix = "" if one else f" (relative to n={ref['devices']}, no n=1 run)"
    print(f"\n{'devices':>8} {'img/s':>10} {'per-dev':>9} "
          f"{'efficiency':>11}{suffix}")
    for p in points:
        per_dev = p["img_per_s"] / p["devices"]
        print(f"{p['devices']:>8} {p['img_per_s']:>10.1f} {per_dev:>9.1f} "
              f"{per_dev / base:>10.1%}")


if __name__ == "__main__":
    if os.environ.get("SCALING_WORKER"):
        _measure()
    else:
        main()
