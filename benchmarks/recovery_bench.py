#!/usr/bin/env python
"""Recovery-plane benchmark: warm-survivor relaunch vs cold restart.

The recovery plane's headline claim (docs/recovery.md): when one rank of
a world dies, parking the survivors and relaunching only the dead slot
(``HOROVOD_RECOVERY_WARM=1``) restores training MTTR-faster than tearing
the whole world down, because survivors keep their process — and with it
the jit caches, device pins, and page-warm parameter state a cold fork
must rebuild. This benchmark kills rank 1 of a 4-rank CPU world at a
fixed step (``HOROVOD_ELASTIC_FAULT``) and measures both paths against
the REAL elastic driver — its park barrier, its slot ledger, its seal
wire, not a mock:

* ``MTTR`` — gap between the last epoch-0 step completed anywhere and
  the first epoch-1 step completed everywhere, from per-rank step logs.
* ``survivor PIDs`` — warm must re-enter with the SAME pid per
  surviving rank; cold forks all four.
* ``bit-exactness`` — both paths must restore the last SEALED commit
  and converge to the same final parameter, warm or cold.

Final line is the JSON contract ``tools/bench_table.py`` renders::

    python benchmarks/recovery_bench.py            # 8 steps, kill @ 3
    python benchmarks/recovery_bench.py --steps 12
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

# repo-root import, the benchmarks/ convention (run as a script)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - sha is cosmetic
        return "unknown"


def _bench_world(steps: int, logdir: str):
    """Per-rank training body (shipped by value through the elastic
    driver): a jitted allreduce step whose compile cost is exactly what
    warm relaunch preserves, logging ``epoch rank step t_done pid`` per
    step for the MTTR scan."""
    import os
    import time

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.elastic import State

    hvd.init()
    rank = hvd.rank()

    # A deliberately WIDE unrolled graph (~4s XLA compile): the
    # compiled-cache half of the warm claim. The jit wrapper is stashed
    # on the package module because that is what a real training loop
    # does — it holds its jitted step across the warm re-entry (same
    # process, same fn identity), so survivors hit the cache while
    # every cold fork pays the compile again, serialized on a small
    # box — exactly the rebuild cost the warm path exists to avoid.
    _local = getattr(hvd, "_recovery_bench_jit", None)
    if _local is None:
        @jax.jit
        def _local(w, s):
            for _ in range(1200):
                w = w + jnp.sin(w + s) * jnp.float32(1e-6)
            return w

        hvd._recovery_bench_jit = _local

    state = State(w=np.zeros(64, np.float32), step=0)

    def train(state):
        log = open(os.path.join(logdir, f"rank{rank}.log"), "a",
                   buffering=1)
        while state.step < steps:
            step = int(state.step)
            if (rank == 1 and world_epoch() == 0
                    and step == int(os.environ["BENCH_KILL_STEP"])):
                os._exit(1)
            w = np.asarray(_local(jnp.asarray(state.w),
                                  np.float32(step + 1)))
            grad = hvd.allreduce(np.full(64, float(step + 1), np.float32),
                                 average=False, name=f"bench.rec.{step}")
            del w  # the jit output only exists to exercise the cache
            state.w = state.w + np.asarray(grad)
            state.step = step + 1
            state.commit()
            state.flush_commits()
            log.write(f"{world_epoch()} {rank} {state.step} "
                      f"{time.monotonic():.6f} {os.getpid()}\n")
        log.close()
        return {"rank": rank, "pid": os.getpid(),
                "epoch": world_epoch(), "w0": float(state.w[0]),
                "restore": state.restore_source}

    out = state.run(train)
    hvd.shutdown()
    return out


_LOG_RE = re.compile(r"^(\d+) (\d+) (\d+) ([0-9.]+) (\d+)$")


def _scan_logs(logdir: str):
    """Parse the per-rank step logs into (epoch, rank, step, t, pid)."""
    rows = []
    for name in os.listdir(logdir):
        if not name.endswith(".log"):
            continue
        with open(os.path.join(logdir, name)) as fh:
            for line in fh:
                m = _LOG_RE.match(line.strip())
                if m:
                    rows.append((int(m[1]), int(m[2]), int(m[3]),
                                 float(m[4]), int(m[5])))
    return rows


def run_mode(warm: bool, steps: int, kill_step: int,
             timeout_s: float) -> dict:
    """One full kill-and-recover run; returns MTTR + survivor facts."""
    from horovod_tpu.elastic import run_elastic

    logdir = tempfile.mkdtemp(prefix="hvd-recbench-")
    env = {
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_NATIVE_CONTROLLER": "0",
        "HOROVOD_CYCLE_TIME": "50",
        "HOROVOD_CKPT_ASYNC": "1",
        "HOROVOD_ELASTIC_FAULT": f"1:{kill_step}",
        "HOROVOD_RECOVERY_WARM": "1" if warm else "0",
        "HOROVOD_RECOVERY_WINDOW_S": "20",
        "HOROVOD_RECONNECT_ATTEMPTS": "4",
        "HOROVOD_RECONNECT_BACKOFF_S": "0.05",
        # tight detection, applied to BOTH modes: the bench compares
        # the RESTART cost, so the shared detection floor must not
        # dilute the ratio
        "HOROVOD_RECONNECT_WINDOW_S": "0.5",
        "BENCH_KILL_STEP": str(kill_step),
    }
    results = run_elastic(
        _bench_world, args=(steps, logdir), np=4, min_np=4,
        max_restarts=2, backoff_s=0.1, timeout_s=timeout_s,
        start_timeout_s=120.0, heartbeat_interval_s=0.2,
        heartbeat_miss_limit=3, env_extra=env)
    rows = _scan_logs(logdir)
    # MTTR: the fault lands after the last epoch-0 step anywhere; the
    # world is back once EVERY rank has an epoch-1 step. First epoch-1
    # completion per rank, the latest of those minus the last epoch-0
    # step time = the outage the training loop observed.
    t0_last = max((t for e, _, _, t, _ in rows if e == 0), default=None)
    first_e1 = {}
    for e, rank, _, t, _ in sorted(rows, key=lambda r: r[3]):
        if e == 1 and rank not in first_e1:
            first_e1[rank] = t
    if t0_last is None or len(first_e1) < 4:
        raise RuntimeError(
            f"{'warm' if warm else 'cold'} run produced no full "
            f"epoch-1 step set (epoch-1 ranks: {sorted(first_e1)})")
    mttr = max(first_e1.values()) - t0_last
    pids = {(e, rank): pid for e, rank, _, _, pid in rows}
    survivors = [r for r in (0, 2, 3)
                 if (0, r) in pids and pids.get((1, r)) == pids[(0, r)]]
    return {
        "mttr_s": mttr,
        "survivor_pids_preserved": sorted(survivors),
        "final_w0": sorted({round(r["w0"], 6) for r in results}),
        "restores": sorted({str(r["restore"]) for r in results
                            if r["epoch"] == 1}),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--kill-step", type=int, default=3)
    ap.add_argument("--timeout-s", type=float, default=240.0)
    args = ap.parse_args(argv)

    expected_w0 = float(4 * sum(range(1, args.steps + 1)))
    modes = {}
    for warm in (False, True):
        name = "warm" if warm else "cold"
        t0 = time.monotonic()
        modes[name] = run_mode(warm, args.steps, args.kill_step,
                               args.timeout_s)
        print(f"{name:4s}: MTTR {modes[name]['mttr_s']:7.3f} s   "
              f"survivor pids preserved "
              f"{modes[name]['survivor_pids_preserved']}   "
              f"(run {time.monotonic() - t0:.1f} s)", flush=True)

    speedup = modes["cold"]["mttr_s"] / max(modes["warm"]["mttr_s"], 1e-9)
    bit_exact = all(m["final_w0"] == [expected_w0]
                    for m in modes.values())
    sealed = all(any("sealed" in s for s in m["restores"])
                 for m in modes.values())
    preserved = modes["warm"]["survivor_pids_preserved"] == [0, 2, 3]
    ok = speedup >= 3.0 and bit_exact and sealed and preserved
    doc = {
        "bench": "recovery_mttr",
        "git": _git_sha(),
        "steps": args.steps,
        "cold_mttr_s": modes["cold"]["mttr_s"],
        "warm_mttr_s": modes["warm"]["mttr_s"],
        "speedup": speedup,
        "survivor_pids_preserved": preserved,
        "bit_exact": bit_exact and sealed,
    }
    print(json.dumps(doc), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
