#!/usr/bin/env python
"""Serving gateway benchmark: continuous batching vs naive dispatch.

Closed-loop load generator against the full serving stack — a real
worker world (``runner.run``), the driver-resident ``ServingPlane``, and
HTTP requests through the gateway — swept over offered-QPS levels in two
modes:

* ``naive``   — ``batch_max=1``: every request dispatches alone (the
  per-request RPC + step overhead is the whole cost model);
* ``batched`` — ``batch_max=N`` (default 32): the continuous
  micro-batcher packs concurrent requests into padded buckets.

Each level runs ``--clients`` keep-alive HTTP clients pacing themselves
to the offered rate; the table reports achieved throughput and p50/p99
ticket-to-response latency. The acceptance claim (ISSUE 11): batched
peak throughput >= 2x naive at equal p99 budget.

Final line is the JSON contract ``tools/bench_table.py`` renders::

    python benchmarks/serving_bench.py                # full sweep
    python benchmarks/serving_bench.py --quick        # one light level
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import statistics
import subprocess
import sys
import threading
import time

import numpy as np

# repo-root import, the benchmarks/ convention (run as a script)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FEATURE_DIM = 1536


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - sha is cosmetic
        return "unknown"


MLP_LAYERS = 8


def _world_fn():
    """Per-rank serving body (shipped by value): a jitted MLP with LARGE
    weight matrices (8 x 1536^2 ~ 75 MB). A batch-1 call is
    weight-streaming-bound — every row pays the full weight traffic — so
    rows packed into one call reuse the streamed weights and per-row
    cost drops ~8x at batch 32 (measured on this image). That weight
    reuse is the mechanism that makes continuous batching pay on real
    serving hardware; the CPU bench reproduces it honestly instead of
    faking a fixed per-call sleep."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from horovod_tpu.serving.worker import serve_worker

    rng = np.random.default_rng(0)
    layers = [rng.standard_normal((FEATURE_DIM, FEATURE_DIM))
              .astype(np.float32) * 0.05 for _ in range(MLP_LAYERS)]

    def mlp(x):
        import jax.numpy as jnp

        for w in layers:
            x = jnp.tanh(x @ w)
        return x

    return serve_worker(
        {"mlp": mlp}, jit=True,
        warmup=(("mlp", (FEATURE_DIM,), "float32"),))


class _Client(threading.Thread):
    """One keep-alive HTTP client pacing itself to its share of the
    offered rate; records (status, latency_s) per request."""

    def __init__(self, port: int, interval_s: float, until: float,
                 payload: bytes) -> None:
        super().__init__(daemon=True)
        self._port = port
        self._interval = interval_s
        self._until = until
        self._payload = payload
        self.records = []

    def run(self) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", self._port,
                                          timeout=30)
        headers = {"Content-Type": "application/octet-stream",
                   "X-Tensor-Name": "mlp",
                   "X-Tensor-Dtype": "float32",
                   "X-Tensor-Shape": str(FEATURE_DIM)}
        next_t = time.monotonic()
        while time.monotonic() < self._until:
            now = time.monotonic()
            if now < next_t:
                time.sleep(next_t - now)
            next_t += self._interval
            t0 = time.monotonic()
            try:
                conn.request("POST", "/v1/infer", body=self._payload,
                             headers=headers)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            except Exception:  # noqa: BLE001 - count as an error sample
                status = -1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", self._port, timeout=30)
            self.records.append((status, time.monotonic() - t0))
        conn.close()


def _loadgen_main(args) -> int:
    """Client-subprocess entry (``--_loadgen``): run this process's
    share of the client fleet and print one JSON line of (status,
    latency) records. Load generation lives OUT of the gateway process
    on purpose — a GIL-sharing client fleet would measure itself, not
    the serving plane."""
    until = time.monotonic() + args.duration
    payload = np.arange(FEATURE_DIM, dtype=np.float32).tobytes()
    interval = args.clients / args.qps
    pool = [_Client(args.port, interval, until, payload)
            for _ in range(args.clients)]
    for c in pool:
        c.start()
    for c in pool:
        c.join(timeout=args.duration + 60)
    records = [[status, round(lat, 6)]
               for c in pool for status, lat in c.records]
    print(json.dumps({"records": records}))
    return 0


# client subprocesses per level: enough to spread the HTTP fleet across
# cores without drowning the box in processes
LOADGEN_PROCS = 4


def _run_level(port: int, offered_qps: float, duration_s: float,
               clients: int) -> dict:
    procs = min(LOADGEN_PROCS, clients)
    per_proc_clients = max(clients // procs, 1)
    cmd_base = [sys.executable, os.path.abspath(__file__), "--_loadgen",
                "--port", str(port),
                "--duration", str(duration_s),
                "--clients", str(per_proc_clients)]
    t0 = time.monotonic()
    children = [subprocess.Popen(
        cmd_base + ["--qps", str(offered_qps / procs)],
        stdout=subprocess.PIPE, text=True) for _ in range(procs)]
    records = []
    for child in children:
        out, _ = child.communicate(timeout=duration_s + 120)
        for line in out.splitlines():
            if line.startswith("{"):
                records.extend(tuple(r) for r in
                               json.loads(line)["records"])
    del t0
    ok = sorted(lat for status, lat in records if status == 200)
    errors = sum(1 for status, _ in records if status != 200)

    def _pct(q: float) -> float:
        if not ok:
            return float("nan")
        return ok[min(int(q * len(ok)), len(ok) - 1)]

    return {
        "offered_qps": offered_qps,
        # rate over the paced window (subprocess startup excluded)
        "achieved_rps": round(len(ok) / duration_s, 1),
        "p50_ms": round(_pct(0.50) * 1e3, 2) if ok else None,
        "p99_ms": round(_pct(0.99) * 1e3, 2) if ok else None,
        "errors": errors,
        "samples": len(records),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--_loadgen", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--np", type=int, default=1, dest="np_",
                    help="serving world size (the dryrun covers 2-proc "
                         "bit-exactness; the bench defaults to 1 for "
                         "throughput)")
    ap.add_argument("--qps", default="50,100,200,400",
                    help="offered-QPS sweep levels")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds per level")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--batch-max", type=int, default=32)
    ap.add_argument("--p99-budget-ms", type=float, default=250.0,
                    help="equal-p99 budget peak throughput is read at")
    ap.add_argument("--quick", action="store_true",
                    help="one light level per mode (CI smoke)")
    args = ap.parse_args(argv)
    if getattr(args, "_loadgen"):
        args.qps = float(args.qps)
        return _loadgen_main(args)
    if args.quick:
        args.qps, args.duration, args.clients = "100", 1.0, 8

    from horovod_tpu.runner import run
    from horovod_tpu.serving import ServingPlane

    os.environ.setdefault("HOROVOD_PLATFORM", "cpu")
    plane = ServingPlane(gateway_port=0, batch_max=args.batch_max,
                         slo_ms=10000.0, deadline_ms=30000.0,
                         queue_max=4096)
    env = plane.env()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    box = {}

    def _driver() -> None:
        try:
            box["results"] = run(_world_fn, np=args.np_, timeout_s=1800.0,
                                 start_timeout_s=120.0)
        except BaseException as exc:  # noqa: BLE001
            box["error"] = f"{type(exc).__name__}: {exc}"

    driver = threading.Thread(target=_driver, daemon=True)
    driver.start()
    try:
        deadline = time.monotonic() + 120.0
        while not plane.stats()["armed"]:
            if "error" in box or time.monotonic() > deadline:
                print(f"serving world failed to arm: {box.get('error')}",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)

        levels = [float(q) for q in args.qps.split(",")]
        sweeps = {}
        for mode, batch_max in (("naive", 1), ("batched", args.batch_max)):
            plane.set_batch_max(batch_max)
            _run_level(plane.gateway_port, levels[0], 0.5,
                       min(args.clients, 8))  # warm the mode's buckets
            rows = []
            for qps in levels:
                row = _run_level(plane.gateway_port, qps, args.duration,
                                 args.clients)
                rows.append(row)
                print(f"{mode:<8} offered {qps:7.0f} qps -> "
                      f"{row['achieved_rps']:7.1f} rps  "
                      f"p50 {row['p50_ms']} ms  p99 {row['p99_ms']} ms  "
                      f"errors {row['errors']}", flush=True)
            sweeps[mode] = rows
    finally:
        plane.stop()
        driver.join(timeout=60.0)
        plane.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # Equal-p99 comparison: hold BOTH modes to the same latency budget.
    # If naive cannot meet the requested budget at any level (its
    # saturation p99 is simply worse), relax to the best p99 naive
    # achieved (+10%) — comparing throughput at a latency the slower
    # mode can actually reach is the fair reading of "at equal p99".
    naive_p99s = [r["p99_ms"] for r in sweeps["naive"]
                  if r["p99_ms"] is not None]
    budget = args.p99_budget_ms
    if naive_p99s and min(naive_p99s) > budget:
        budget = round(min(naive_p99s) * 1.1, 1)
        print(f"naive never met p99<={args.p99_budget_ms:.0f}ms; "
              f"comparing at its achievable budget {budget}ms",
              flush=True)

    def _peak(rows) -> float:
        within = [r["achieved_rps"] for r in rows
                  if r["p99_ms"] is not None and r["p99_ms"] <= budget]
        return max(within) if within else 0.0

    naive_peak = _peak(sweeps["naive"])
    batched_peak = _peak(sweeps["batched"])
    speedup = round(batched_peak / naive_peak, 2) if naive_peak else None
    print(f"peak within p99<={budget:.0f}ms: naive "
          f"{naive_peak:.1f} rps, batched {batched_peak:.1f} rps "
          f"-> {speedup}x", flush=True)
    result = {
        "metric": "serving_continuous_batching_speedup",
        "value": speedup,
        "unit": "x",
        "vs_baseline": None,
        "live": True,
        "p99_budget_ms": budget,
        "batch_max": args.batch_max,
        "np": args.np_,
        "clients": args.clients,
        "duration_s": args.duration,
        "serving": sweeps,
        "worker_stats": box.get("results"),
        "captured_at": round(time.time(), 1),
        "git_sha": _git_sha(),
    }
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
