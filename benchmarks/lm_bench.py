#!/usr/bin/env python
"""Transformer-LM synthetic benchmark: tokens/s/device + MFU.

The reference's benchmark family is conv nets (its 2019 vintage predates
LM training at scale); this is the framework's second flagship workload —
matmul-dominated, so it shows what the MXU can actually sustain where
ResNet-50 at bs32 is bandwidth-bound (docs/benchmarks.md "Why bs32
caps"). Same measurement protocol as ``bench.py``
(``examples/pytorch_synthetic_benchmark.py:24-110``): synthetic data,
10 warmup batches, ``--num-iters`` x ``--num-batches-per-iter`` timed
batches, mean ± 1.96σ; the step is the framework's product path
(``hvd.DistributedOptimizer`` over the data axis, jit + shard_map,
donated buffers, AOT-compiled).

Defaults are GPT-2-small-shaped (12 layers, 12 heads, d_model 768,
d_ff 3072, seq 1024, vocab 32768) with the Pallas flash-attention kernel
(``--attention dense`` for the XLA-fused baseline; the kernel
auto-interprets off-TPU so CPU CI drives the identical code path).

Prints ONE JSON line like bench.py, metric
``transformer_lm_tokens_per_sec_per_device`` (vs_baseline null — the
reference publishes no LM figure).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawTextHelpFormatter)
    parser.add_argument("--num-layers", type=int, default=12)
    parser.add_argument("--num-heads", type=int, default=12)
    parser.add_argument("--d-model", type=int, default=768)
    parser.add_argument("--d-ff", type=int, default=3072)
    parser.add_argument("--vocab-size", type=int, default=32768)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--batch-size", type=int, default=8,
                        help="sequences per device")
    parser.add_argument("--attention", default="flash",
                        choices=["dense", "flash"])
    parser.add_argument("--remat", action="store_true",
                        help="jax.checkpoint each block (long-seq memory)")
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--warm-init-cache", action="store_true",
                        default=False,
                        help="build this config's host-init cache entry "
                             "on CPU and exit before any accelerator "
                             "contact (see bench.py --warm-init-cache)")
    parser.add_argument("--warm-devices", type=int, default=1,
                        help="device count the warmed entry targets "
                             "(see bench.py --warm-devices)")
    return parser.parse_args(argv)


def _init_cache_path(args, global_batch) -> str:
    """Host-init cache entry for this LM config (shared policy:
    ``core.platform.init_cache_path``; this file is hashed in).
    Deliberately NOT keyed by ``--attention``/``--remat``: params come
    from a dense-clone init and tokens depend only on (batch, seq,
    vocab), so flash and dense share one entry."""
    from horovod_tpu.core.platform import init_cache_path

    cfg = (f"lm_{args.num_layers}x{args.num_heads}_d{args.d_model}"
           f"_ff{args.d_ff}_v{args.vocab_size}_s{args.seq_len}"
           f"_gb{global_batch}")
    return init_cache_path(cfg, extra_sources=[os.path.abspath(__file__)])


def main() -> None:
    args = _parse_args()

    if args.warm_init_cache:
        os.environ.setdefault("HOROVOD_BENCH_PLATFORM", "cpu")

    import jax

    platform_pin = os.environ.get("HOROVOD_BENCH_PLATFORM")
    if platform_pin:
        jax.config.update("jax_platforms", platform_pin)
    from bench import (
        _add_mfu_fields,
        _git_head as _git_sha,
        _log as log,
        _maybe_dump_hlo,
        _maybe_profile_one_batch,
        _setup_accelerator_cache,
        _step_flops_of,
    )

    _setup_accelerator_cache(jax)
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd  # first: installs the jax compat aliases

    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.core.platform import host_init_cached, init_on_host_cpu
    from horovod_tpu.models import TransformerLM, lm_loss

    hvd.init()
    n_dev = hvd.local_device_count()
    mesh = hvd.parallel.data_parallel_mesh()
    log(f"TransformerLM: {args.num_layers}L/{args.num_heads}H/"
        f"d{args.d_model}/ff{args.d_ff}, vocab {args.vocab_size}, "
        f"seq {args.seq_len}, batch {args.batch_size}/device, "
        f"attention={args.attention}, devices: {n_dev} "
        f"({jax.devices()[0].platform})")

    model = TransformerLM(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model, d_ff=args.d_ff,
        max_seq_len=args.seq_len, attention=args.attention,
        remat=args.remat)
    # see bench.py: warm mode sizes arrays for the --warm-devices target
    # topology, not the host backend it happens to run on
    global_batch = args.batch_size * (args.warm_devices
                                      if args.warm_init_cache else n_dev)

    def synthesize_and_init():
        rng = jax.random.PRNGKey(0)
        tokens = jax.random.randint(
            rng, (global_batch, args.seq_len), 0, args.vocab_size,
            dtype=jnp.int32)
        # init with dense attention on tiny tokens: the pallas kernel's
        # shapes are irrelevant to parameter shapes, and interpreting it
        # on the host init backend would be minutes of wasted work
        init_model = model.clone(attention="dense")
        variables = init_model.init(jax.random.PRNGKey(1), tokens[:2, :8])
        return tokens, variables

    cache_path = _init_cache_path(args, global_batch)
    if args.warm_init_cache:
        host_init_cached(cache_path, synthesize_and_init, log=log)
        log("init cache warmed; exiting without accelerator contact")
        return

    placed = init_on_host_cpu(
        lambda: host_init_cached(cache_path, synthesize_and_init, log=log),
        (NamedSharding(mesh, P("data")), NamedSharding(mesh, P())),
        log=log)
    if placed is not None:
        tokens, variables = placed
    else:
        log("host-CPU init/placement unavailable (see warning above); "
            "initializing on device")
        tokens, variables = synthesize_and_init()
    params = variables["params"]
    log("model initialized")

    opt = hvd.DistributedOptimizer(
        optax.adamw(3e-4, weight_decay=0.01), axis_name="data")
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def train_step(params, opt_state, tokens):
        def f(p):
            return lm_loss(model.apply({"params": p}, tokens), tokens)

        loss, grads = jax.value_and_grad(f)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, "data"))

    step = jax.jit(
        shard_map(train_step, mesh=mesh,
                  in_specs=(P(), P(), P("data")),
                  out_specs=(P(), P(), P())),
        donate_argnums=(0, 1))

    log("Compiling LM train step (AOT)...")
    compiled = step.lower(params, opt_state, tokens).compile()
    step_flops = _step_flops_of(compiled, log)
    _maybe_dump_hlo(compiled, log)

    loss = None

    def run_batch():
        nonlocal params, opt_state, loss
        params, opt_state, loss = compiled(params, opt_state, tokens)

    log(f"Running {args.num_warmup_batches} warmup batches...")
    for _ in range(args.num_warmup_batches):
        run_batch()
    jax.block_until_ready(params)

    _maybe_profile_one_batch(run_batch,
                             lambda: jax.block_until_ready(params), log)

    tok_secs = []
    tokens_per_batch = global_batch * args.seq_len
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            run_batch()
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        rate = tokens_per_batch * args.num_batches_per_iter / dt
        tok_secs.append(rate)
        log(f"Iter #{i}: {rate:.0f} tokens/sec total")

    mean = float(np.mean(tok_secs))
    conf = float(1.96 * np.std(tok_secs))
    per_device = mean / n_dev
    log(f"Tokens/sec/device: {per_device:.0f} +- {conf / n_dev:.0f} "
        f"(loss {float(loss):.3f})")

    result = {
        "metric": "transformer_lm_tokens_per_sec_per_device",
        "value": round(per_device, 1),
        "unit": "tokens/s",
        "vs_baseline": None,  # the reference publishes no LM figure
        "live": True,
        "attention": args.attention,
        "seq_len": args.seq_len,
        "batch_size": args.batch_size,
        "n_devices": n_dev,
        "captured_at": round(time.time(), 1),
        "git_sha": _git_sha(),
    }
    # steps/s, not tokens/s: step_flops is the whole per-device step
    _add_mfu_fields(result, step_flops, mean / tokens_per_batch,
                    jax.devices()[0], log)
    print(json.dumps(result))
    hvd.shutdown()


if __name__ == "__main__":
    main()
