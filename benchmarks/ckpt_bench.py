#!/usr/bin/env python
"""Checkpoint commit-path benchmark: synchronous push vs async pipeline.

The checkpoint plane's headline claim (docs/checkpoint.md): the stall a
``State.commit()`` imposes on the training loop is O(snapshot) —
independent of state size — once the persist rides the async chunked
stream, while the legacy synchronous push stalls linearly in the pickled
tree. This benchmark measures both against a REAL driver-side
:class:`~horovod_tpu.elastic.health.ElasticService` (its seal ledger,
its wire, its HMAC framing — not a mock), at three state sizes:

* ``sync push``    — pickle + one whole-tree ``("commit", ...)`` request,
  timed end to end: the stall the legacy path charges the step loop.
* ``async submit`` — ``AsyncCommitter.submit()`` return time: the stall
  the async path charges the step loop (a slot store + notify).
* ``async stream`` — submit until the driver's ledger SEALS the commit:
  the durability latency the background thread pays instead.

Medians of ``--iters`` runs per cell. Final line is the JSON contract
``tools/bench_table.py`` renders::

    python benchmarks/ckpt_bench.py            # 1 / 8 / 32 MB
    python benchmarks/ckpt_bench.py --quick    # 1 / 4 MB, fewer iters
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import statistics
import subprocess
import sys
import time

import numpy as np

# repo-root import, the benchmarks/ convention (run as a script)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - sha is cosmetic
        return "unknown"


def _tree(mb: float) -> dict:
    """A committed-state stand-in of ~mb MB: one float32 parameter blob
    plus the scalar leaves a real State carries."""
    n = max(int(mb * (1 << 20) / 4), 1)
    rng = np.random.default_rng(42)
    return {"w": rng.standard_normal(n).astype(np.float32), "step": 7}


def bench_size(addr, secret, mb: float, iters: int,
               commit_base: int) -> dict:
    """One size cell against the live service; returns median seconds."""
    from horovod_tpu.ckpt.committer import AsyncCommitter
    from horovod_tpu.runner.network import BasicClient

    tree = _tree(mb)
    sync_s, submit_s, stream_s = [], [], []

    # legacy synchronous path: the stall is pickle + the whole-tree frame
    client = BasicClient(addr, secret=secret, attempts=3, timeout_s=120.0)
    try:
        for _ in range(iters):
            t0 = time.monotonic()
            payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
            client.request(("commit", 0, {"commit_no": 0}, payload))
            sync_s.append(time.monotonic() - t0)
    finally:
        client.close()

    # async path: the training-loop stall is submit(); the background
    # thread pays the pickle + chunk stream, measured to the SEAL ack
    committer = AsyncCommitter(addr, rank=0, world=1, secret=secret)
    try:
        for i in range(iters):
            no = commit_base + i + 1
            t0 = time.monotonic()
            committer.submit(no, tree, 0)
            submit_s.append(time.monotonic() - t0)
            if not committer.wait_idle(timeout_s=120.0):
                raise RuntimeError(f"async stream never drained ({mb} MB)")
            if committer.last_sealed < no:
                raise RuntimeError(
                    f"commit {no} never sealed (last_sealed="
                    f"{committer.last_sealed})")
            stream_s.append(time.monotonic() - t0)
    finally:
        committer.close()

    return {
        "state_mb": mb,
        "payload_bytes": len(pickle.dumps(tree,
                                          protocol=pickle.HIGHEST_PROTOCOL)),
        "sync_push_s": statistics.median(sync_s),
        "async_submit_s": statistics.median(submit_s),
        "async_stream_s": statistics.median(stream_s),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--quick", action="store_true",
                    help="2 small sizes, fewer iters (CI smoke)")
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args(argv)
    sizes = (1.0, 4.0) if args.quick else (1.0, 8.0, 32.0)
    iters = args.iters or (2 if args.quick else 3)

    from horovod_tpu.elastic.health import ElasticService
    from horovod_tpu.runner.network import make_secret

    secret_hex = make_secret()
    secret = bytes.fromhex(secret_hex)
    service = ElasticService(secret, heartbeat_interval_s=1.0,
                             miss_limit=1000)
    addr = ("127.0.0.1", service.port)
    rows = []
    try:
        for i, mb in enumerate(sizes):
            row = bench_size(addr, secret, mb, iters,
                             commit_base=1000 * i)
            rows.append(row)
            print(f"state {mb:6.1f} MB: sync push "
                  f"{row['sync_push_s'] * 1e3:8.2f} ms   async submit "
                  f"{row['async_submit_s'] * 1e3:8.3f} ms   async stream "
                  f"{row['async_stream_s'] * 1e3:8.2f} ms", flush=True)
    finally:
        service.shutdown()

    # the claim, asserted: submit stall must NOT scale with state size
    # (<= 10x from smallest to largest while the payload grows 32x, and
    # always well under the sync push of the same size)
    small, large = rows[0], rows[-1]
    flat = (large["async_submit_s"]
            <= max(small["async_submit_s"] * 10, 5e-3))
    doc = {
        "bench": "ckpt_commit_stall",
        "git": _git_sha(),
        "iters": iters,
        "rows": rows,
        "stall_independent_of_size": bool(flat),
    }
    print(json.dumps(doc), flush=True)
    return 0 if flat else 1


if __name__ == "__main__":
    sys.exit(main())
