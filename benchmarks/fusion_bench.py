#!/usr/bin/env python
"""Tensor-fusion micro-benchmark: many small eager allreduces, fused vs not.

The reference's core eager-path performance claim is that batching small
tensors into one fusion buffer amortizes per-op overhead
(``docs/tensor-fusion.md``; the 64 MB ``HOROVOD_FUSION_THRESHOLD`` default).
This benchmark measures that claim for this framework's two eager data
planes on a 2-process world:

* ``host``  — numpy-over-TCP exchange through the controller: per-op cost is
  a TCP payload round-trip, so fusion collapses M round-trips into one.
* ``xla``   — compiled XLA collectives (gloo on CPU, ICI on pods): per-op
  cost is a dispatch + compile-cache lookup per buffer; fusion collapses M
  dispatches into one and pads into the bucketed compile cache.

Usage:  python benchmarks/fusion_bench.py [--tensors 64] [--elems 25000]
                                          [--rounds 12] [--subbuffers 1,2,4]
                                          [--no-fused-apply]

Prints one table row per (plane, threshold) with tensors/s and speedup,
then the sub-buffer OVERLAP table (docs/tensor-fusion.md): tensors/s,
achieved overlap ratio (measured negotiate-while-flushing seconds over
flush-execute seconds, off the obs registry), and peak in-flight depth
per ``HOROVOD_FUSION_SUBBUFFERS`` count, then the fused REDUCE+APPLY
table (two-dispatch vs apply-fused ``hvd.apply_step`` rounds: tensors/s,
achieved overlap ratio, and measured apply dispatches per round — the
fused plane lands applied parameters, collapsing one apply program per
LEAF into one per BATCH). The final stdout line is one JSON summary of
the overlap/apply tables (the repo tool contract). Wire bytes in the main table
are MEASURED per round off the obs registry counters (the single
accounting definition: ``horovod_eager_wire_bytes_post_total`` on the
device plane, ``horovod_wire_tx/rx_bytes_total`` on the host TCP plane);
the analytic model survives only in the codec footer, which has no timed
world to measure. The driver for each world is this same file
re-executed with ``HOROVOD_RANK`` set (the launcher-env protocol of
``core/topology.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

# repo root on the path once, for the byte-ledger's bucket import (the
# worker body does its own insert before importing the full package)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _worker() -> None:
    """Rank body: submit --tensors async allreduces per round, synchronize
    all, repeat; report wall seconds for the timed rounds on rank 0."""
    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if os.environ.get("FUSION_BENCH_JAX_COORD"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            os.environ["FUSION_BENCH_JAX_COORD"],
            num_processes=int(os.environ["HOROVOD_SIZE"]),
            process_id=int(os.environ["HOROVOD_RANK"]))
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import horovod_tpu as hvd

    n_tensors = int(os.environ["FUSION_BENCH_TENSORS"])
    n_elems = int(os.environ["FUSION_BENCH_ELEMS"])
    rounds = int(os.environ["FUSION_BENCH_ROUNDS"])
    hvd.init()

    # Wire-byte measurement off the obs registry (docs/metrics.md): one
    # accounting definition shared with /metrics and the BENCH json,
    # instead of this file re-deriving bucket math that can drift.
    from horovod_tpu.obs import registry as _registry

    def _fam_total(snap, family):
        fam = snap.get(family)
        return sum(s["value"] for s in fam["samples"]) if fam else 0
    if os.environ.get("FUSION_BENCH_INPUT") == "jax":
        # device-resident submissions: on the xla plane these ride the
        # on-chip pack→psum→unpack path with zero host transfers
        import jax.numpy as jnp

        tensors = [jnp.full((n_elems,), float(i), jnp.float32)
                   for i in range(n_tensors)]
        jax.block_until_ready(tensors)
    else:
        tensors = [np.full((n_elems,), float(i), np.float32)
                   for i in range(n_tensors)]

    def one_round(tag: str) -> None:
        handles = [hvd.allreduce_async(t, average=False,
                                       name=f"fb.{tag}.{i}")
                   for i, t in enumerate(tensors)]
        outs = [hvd.synchronize(h) for h in handles]
        # device-resident results are lazily-dispatched jax.Arrays — the
        # round is only done when they are, else the timer measures
        # dispatch throughput and the execution tail escapes it
        jax.block_until_ready([o for o in outs
                               if not isinstance(o, np.ndarray)])

    if os.environ.get("FUSION_BENCH_APPLY"):
        # Apply-fused measurement (docs/tensor-fusion.md §fused apply):
        # each round is one hvd.apply_step over n_tensors parameter
        # leaves — the engine lands applied parameters; with
        # HOROVOD_FUSED_APPLY=1 one reduce+apply program per batch,
        # otherwise the two-dispatch reference (reduce + per-leaf apply)
        tx = hvd.DistributedOptimizer(hvd.fused_sgd(0.01))
        params = {f"t{i}": np.full((n_elems,), 0.5, np.float32)
                  for i in range(n_tensors)}
        opt_state = tx.init(params)

        def one_round(tag: str) -> None:
            nonlocal params, opt_state
            grads = {f"t{i}": t for i, t in enumerate(tensors)}
            params, opt_state = hvd.apply_step(tx, grads, opt_state,
                                               params)

    one_round("warm0")  # warm the compile cache / connections
    one_round("warm1")
    snap0 = _registry().snapshot()
    t0 = time.perf_counter()
    for r in range(rounds):
        one_round(str(r))
    dt = time.perf_counter() - t0
    snap1 = _registry().snapshot()
    # per-rank wire bytes this run actually moved during the timed
    # rounds: device plane = estimated on-wire bucket bytes; host plane =
    # bytes crossing the TCP wire both ways (payloads + cycle metadata —
    # that IS the host plane's wire)
    wire = _fam_total(snap1, "horovod_eager_wire_bytes_post_total") - \
        _fam_total(snap0, "horovod_eager_wire_bytes_post_total")
    if wire == 0:
        wire = sum(_fam_total(snap1, f) - _fam_total(snap0, f)
                   for f in ("horovod_wire_tx_bytes_total",
                             "horovod_wire_rx_bytes_total"))
    from horovod_tpu.ops.engine import get_engine

    eng = get_engine()
    overlap = eng.overlap_stats()
    apply_stats = eng.apply_stats()
    # apply-program dispatches and achieved overlap seconds during the
    # TIMED rounds only (registry deltas, like the wire bytes) — the
    # dispatches-per-step and overlap-window columns
    apply_disp = _fam_total(snap1, "horovod_apply_dispatches_total") - \
        _fam_total(snap0, "horovod_apply_dispatches_total")
    timed_overlap = _fam_total(snap1, "horovod_overlap_seconds_total") - \
        _fam_total(snap0, "horovod_overlap_seconds_total")
    if hvd.rank() == 0:
        print(json.dumps({"seconds": dt,
                          "tensors_per_s": rounds * n_tensors / dt,
                          "wire_bytes_per_round": wire / rounds,
                          "overlap": overlap,
                          "timed_overlap_seconds": timed_overlap,
                          "apply": apply_stats,
                          "apply_dispatches_per_round":
                              apply_disp / rounds}), flush=True)
    hvd.shutdown()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wire_bytes_per_round(plane: str, threshold: int, tensors: int,
                          elems: int, codec: str = "none") -> int:
    """Per-rank wire-byte accounting for one round of this benchmark —
    the fusion claim is about PER-OP overhead, but the byte ledger shows
    what each configuration actually moves (incl. the bucket padding the
    xla plane pays and the ~4x the int8 codec saves; docs/compression.md).

    host plane: payload crosses the TCP wire twice (rank->controller,
    controller->rank), unpadded. xla plane: the SAME power-of-two bucket
    function the plane allocates with (ops.xla_plane._next_bucket), with
    the fusion threshold packing greedily by payload bytes exactly like
    the negotiator's fusion loop — a round larger than the threshold
    splits into several buckets, not one oversized one. Costed with the
    ring all-reduce model (2B(n-1)/n, n=2 here); the int8 codec's ledger
    adds its f32 pmax scale exchange and halves nothing else it doesn't
    pay."""
    from horovod_tpu.ops.compression import Compression
    from horovod_tpu.ops.xla_plane import _next_bucket

    n = 2  # this benchmark's world size
    if plane == "host":
        return tensors * elems * 4 * 2

    if threshold > 0:
        # greedy byte-packing, as the negotiator fuses: each bucket takes
        # as many whole tensors as fit under the threshold
        per_bucket = max(1, threshold // (elems * 4))
        buckets = []
        left = tensors
        while left > 0:
            take = min(per_bucket, left)
            buckets.append(_next_bucket(take * elems))
            left -= take
    else:
        buckets = [_next_bucket(elems)] * tensors
    total = 0
    for b in buckets:
        if codec in ("int8", "fp8"):
            # scatter leg (all_to_all) + gather leg (all_gather) of the
            # 1-byte payload, plus the f32 block-scale pmax (all-reduce);
            # scale count comes from the codec's OWN block geometry
            block, padded = Compression.lookup(codec).block_layout(b, n)
            scales_b = (padded // block) * 4
            total += 2 * (b * (n - 1) // n) + 2 * scales_b * (n - 1) // n
        else:
            total += 2 * b * 4 * (n - 1) // n  # ring all-reduce of f32
    return total


def _run_world(plane: str, threshold: int, args, tensor_input="numpy",
               subbuffers: int = 1,
               force_python_controller: bool = False,
               apply_mode: str = "") -> dict:
    port = _free_port()
    coord = f"127.0.0.1:{_free_port()}" if plane == "xla" else ""
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": "2",
            "HOROVOD_LOCAL_RANK": str(rank), "HOROVOD_LOCAL_SIZE": "2",
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HOROVOD_DATA_PLANE": plane,
            "HOROVOD_FUSION_THRESHOLD": str(threshold),
            "HOROVOD_CYCLE_TIME": "1",
            "HOROVOD_FUSION_SUBBUFFERS": str(subbuffers),
            "FUSION_BENCH_WORKER": "1",
            "FUSION_BENCH_TENSORS": str(args.tensors),
            "FUSION_BENCH_ELEMS": str(args.elems),
            "FUSION_BENCH_ROUNDS": str(args.rounds),
            "FUSION_BENCH_JAX_COORD": coord,
            "FUSION_BENCH_INPUT": tensor_input,
        })
        if apply_mode:
            # apply-fused measurement (docs/tensor-fusion.md §fused
            # apply): rounds are hvd.apply_step calls; "fused" lands
            # applied params from one reduce+apply program per batch,
            # "two-dispatch" runs the reference reduce + per-leaf apply
            env["FUSION_BENCH_APPLY"] = "1"
            env["HOROVOD_FUSED_APPLY"] = \
                "1" if apply_mode == "fused" else "0"
        if subbuffers > 1 or force_python_controller:
            # the flush pipeline needs the Python controller wire
            # (ops/engine._arm_flush_pipeline degrade rule); the overlap
            # table pins it for its subbuffers=1 BASELINE too, so the
            # speedup column measures sub-buffering alone, not a
            # native-vs-Python controller swap
            env["HOROVOD_NATIVE_CONTROLLER"] = "0"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(f"worker failed:\n{err}")
    return json.loads(outs[0][0].strip().splitlines()[-1])


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tensors", type=int, default=64,
                        help="small tensors per round (grad-sized count)")
    parser.add_argument("--elems", type=int, default=25_000,
                        help="float32 elements per tensor (~100 KB)")
    parser.add_argument("--rounds", type=int, default=12)
    parser.add_argument("--subbuffers", default="1,2,4",
                        help="comma-separated HOROVOD_FUSION_SUBBUFFERS "
                             "counts for the overlap table (empty skips "
                             "it; docs/tensor-fusion.md)")
    parser.add_argument("--fused-apply", dest="fused_apply", default=True,
                        action="store_true",
                        help="run the fused reduce+apply table "
                             "(two-dispatch vs apply-fused hvd.apply_step "
                             "rounds; docs/tensor-fusion.md §fused apply)")
    parser.add_argument("--no-fused-apply", dest="fused_apply",
                        action="store_false")
    args = parser.parse_args()

    mb = args.tensors * args.elems * 4 / 1e6
    print(f"# fusion micro-benchmark: 2 ranks, {args.tensors} x "
          f"{args.elems * 4 / 1e3:.0f} KB tensors/round ({mb:.1f} MB), "
          f"{args.rounds} rounds")
    print(f"{'plane':<10} {'threshold':>10} {'tensors/s':>10} {'speedup':>8} "
          f"{'wire MB/rd':>10}")
    # xla+jax = device-resident submissions (the TPU deployment shape:
    # jax.Arrays in, on-chip pack→psum→unpack, jax.Arrays out)
    for plane, tensor_input in (("host", "numpy"), ("xla", "numpy"),
                                ("xla", "jax")):
        base = None
        for threshold in (0, 64 * 1024 * 1024):
            r = _run_world(plane, threshold, args, tensor_input)
            if base is None:
                base = r["tensors_per_s"]
            label = "0" if threshold == 0 else "64MiB"
            name = plane if tensor_input == "numpy" else f"{plane}+jax"
            # measured per-rank wire bytes off the obs registry (one
            # accounting definition with /metrics and the BENCH json)
            wire_mb = r["wire_bytes_per_round"] / 1e6
            print(f"{name:<10} {label:>10} {r['tensors_per_s']:>10.0f} "
                  f"{r['tensors_per_s'] / base:>7.1f}x {wire_mb:>9.1f}M",
                  flush=True)

    # Sub-buffer overlap table (docs/tensor-fusion.md): step time and
    # ACHIEVED overlap ratio — measured negotiate-while-flushing seconds
    # over flush-execute seconds, straight off the engine's pipeline
    # counters — per HOROVOD_FUSION_SUBBUFFERS count on the host plane
    # (the fused threshold; sub-buffering generalizes the single flush).
    summary = {"tool": "fusion_bench", "tensors": args.tensors,
               "elems": args.elems, "rounds": args.rounds,
               "overlap_table": [], "apply_table": []}
    counts = [int(c) for c in args.subbuffers.split(",") if c.strip()]
    if counts:
        print(f"\n# sub-buffer overlap (host plane, 64MiB threshold)")
        print(f"{'subbuffers':>10} {'tensors/s':>10} {'speedup':>8} "
              f"{'overlap':>8} {'inflight':>8}")
        base = None
        for n_sub in counts:
            r = _run_world("host", 64 * 1024 * 1024, args,
                           subbuffers=n_sub,
                           force_python_controller=True)
            if base is None:
                base = r["tensors_per_s"]
            ov = r["overlap"]
            busy = ov["execute_busy_seconds"]
            ratio = ov["overlap_seconds"] / busy if busy > 0 else 0.0
            summary["overlap_table"].append({
                "subbuffers": n_sub,
                "tensors_per_s": round(r["tensors_per_s"], 1),
                "overlap_ratio": round(ratio, 3),
                "inflight_peak": ov["inflight_peak"]})
            print(f"{n_sub:>10} {r['tensors_per_s']:>10.0f} "
                  f"{r['tensors_per_s'] / base:>7.1f}x "
                  f"{100 * ratio:>6.0f}% {ov['inflight_peak']:>8}",
                  flush=True)

    # Apply-fused table (docs/tensor-fusion.md §fused apply): the same
    # workload as hvd.apply_step rounds — two-dispatch (reduce + one
    # apply program per leaf) vs apply-fused (the engine lands applied
    # parameters, one reduce+apply program per batch) under the overlap
    # pipeline, with the measured dispatches-per-step column.
    if counts and args.fused_apply:
        n_sub = max(counts)
        print(f"\n# fused reduce+apply (host plane, 64MiB threshold, "
              f"subbuffers={n_sub}; 'overlap' counts the whole flush —")
        print(f"# which under 'fused' INCLUDES the update math the "
              f"two-dispatch mode runs un-overlapped on the main thread)")
        print(f"{'mode':>14} {'tensors/s':>10} {'speedup':>8} "
              f"{'overlap':>8} {'ov ms/rd':>9} {'disp/rd':>8}")
        base = None
        for mode in ("two-dispatch", "fused"):
            r = _run_world("host", 64 * 1024 * 1024, args,
                           subbuffers=n_sub,
                           force_python_controller=True,
                           apply_mode=mode)
            if base is None:
                base = r["tensors_per_s"]
            ov = r["overlap"]
            busy = ov["execute_busy_seconds"]
            ratio = ov["overlap_seconds"] / busy if busy > 0 else 0.0
            ov_ms = 1e3 * r["timed_overlap_seconds"] / args.rounds
            disp = r["apply_dispatches_per_round"]
            summary["apply_table"].append({
                "mode": mode,
                "tensors_per_s": round(r["tensors_per_s"], 1),
                "overlap_ratio": round(ratio, 3),
                "overlap_ms_per_round": round(ov_ms, 3),
                "apply_dispatches_per_round": round(disp, 2),
                "fused_batches": r["apply"]["fused_batches"]})
            print(f"{mode:>14} {r['tensors_per_s']:>10.0f} "
                  f"{r['tensors_per_s'] / base:>7.1f}x "
                  f"{100 * ratio:>6.0f}% {ov_ms:>9.2f} {disp:>8.1f}",
                  flush=True)
    # codec byte ledger (no timed run: byte accounting is analytic; the
    # timed int8 world needs >=2 jax processes and is covered by
    # benchmarks/compression_bench.py's HLO audit)
    fused = 64 * 1024 * 1024
    f32_b = _wire_bytes_per_round("xla", fused, args.tensors, args.elems)
    int8_b = _wire_bytes_per_round("xla", fused, args.tensors, args.elems,
                                   codec="int8")
    print(f"# fused-bucket wire bytes: f32 {f32_b / 1e6:.1f} MB vs int8 "
          f"codec {int8_b / 1e6:.1f} MB ({f32_b / int8_b:.1f}x reduction)",
          flush=True)
    summary["codec_wire_bytes"] = {"f32": f32_b, "int8": int8_b}
    # final-line JSON (the repo tool contract, like tools/lint.sh)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    if os.environ.get("FUSION_BENCH_WORKER"):
        _worker()
    else:
        main()
