#!/usr/bin/env python
"""Quantized-allreduce micro-benchmark: wire bytes + numerical agreement.

The EQuARX data plane (``ops.spmd.quantized_allreduce``) claims ~4x fewer
collective wire bytes than f32 at a bounded block-relative error. This
benchmark AUDITS both claims from the compiled programs themselves, on the
virtual 8-device CPU mesh (identical lowering to the ICI collectives):

* **wire bytes** — every collective instruction in the compiled HLO is
  parsed (operand shape x dtype width) and costed with the standard ring
  model (all-reduce moves 2B(n-1)/n per rank, reduce-scatter/all-to-all
  B(n-1)/n, all-gather B_out(n-1)/n), so the reported reduction counts
  the quantized path's OWN overheads: the f32 ``pmax`` scale exchange and
  the int8 all-gather return leg, not just the headline payload cast.
* **agreement** — flat ``pmean`` vs quantized mean on random data, checked
  against the documented bound (per-element: across-ranks block absmax x
  ``codec.ERROR_BOUND``; int8: 1/127 — one half-step from quantization
  plus one half-step from re-quantizing the averaged sum).

Usage:  python benchmarks/compression_bench.py [--codec int8] [--devices 8]

Prints one table row per bucket size in the standard sweep (64 KiB ..
16 MiB of f32, the fusion-buffer range ``docs/tensor-fusion.md`` targets)
plus one JSON summary line:

  {"metric": "int8_allreduce_wire_byte_reduction", "value": R, ...}

where R is the MINIMUM reduction across the sweep (the honest headline).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# dtype byte widths for HLO shape strings like f32[8,512] / s8[4096]
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8,
}

def _shape_bytes(shape: str) -> int:
    m = re.match(r"([a-z0-9]+)\[([0-9,]*)\]", shape)
    if not m or m.group(1) not in _DTYPE_BYTES:
        return 0
    dims = m.group(2)
    elems = 1
    for d in dims.split(","):
        if d:
            elems *= int(d)
    return elems * _DTYPE_BYTES[m.group(1)]


def collective_wire_bytes(hlo: str, n: int) -> dict:
    """Per-rank ring-model wire bytes of every collective in ``hlo``,
    grouped by op kind. Parses instruction lines of the form
    ``<result-shape(s)> <op>(...)`` — the result may be a TUPLE (CPU
    all-to-all returns one buffer per peer), so every ``dtype[dims]``
    token in the result type is summed. ``-start`` spellings count,
    ``-done`` halves carry no new traffic."""
    out: dict = {}
    for line in hlo.splitlines():
        m = re.search(
            r"=\s*(.*?)\s(all-reduce|reduce-scatter|all-gather|all-to-all|"
            r"collective-permute)(-start)?\(", line)
        if not m:
            continue
        op = m.group(2)
        nbytes = sum(_shape_bytes(s) for s in
                     re.findall(r"[a-z0-9]+\[[0-9,]*\]", m.group(1)))
        if op == "all-reduce":
            wire = 2 * nbytes * (n - 1) // n
        elif op == "all-gather":
            wire = nbytes * (n - 1) // n  # result IS the gathered output
        elif op == "reduce-scatter":
            wire = nbytes * (n - 1)  # result is the 1/n shard
        elif op == "collective-permute":
            wire = nbytes
        else:  # all-to-all: result total == payload total
            wire = nbytes * (n - 1) // n
        out[op] = out.get(op, 0) + wire
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--codec", default="int8", choices=["int8", "fp8"])
    parser.add_argument("--devices", type=int, default=8)
    args = parser.parse_args()

    from horovod_tpu.core.platform import pin_cpu_platform

    pin_cpu_platform(args.devices)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.obs.tensorwatch import snr_db
    from horovod_tpu.ops import spmd
    from horovod_tpu.ops.compression import Compression

    codec = Compression.lookup(args.codec)
    n = args.devices
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    # standard bucket sweep: 64 KiB .. 16 MiB of f32 per device
    sweep = [16 * 1024, 256 * 1024, 1024 * 1024, 4 * 1024 * 1024]

    print(f"# quantized allreduce audit: {args.codec}, {n}-device mesh, "
          f"block={codec.BLOCK}")
    print(f"{'bucket':>10} {'flat B/rank':>12} {'quant B/rank':>12} "
          f"{'reduction':>9} {'max err':>10} {'bound':>10} "
          f"{'meas SNR':>9} {'ok':>3}")

    worst_reduction = None
    worst_err_ratio = 0.0
    worst_snr = None  # measured end-to-end wire SNR, min over the sweep
    rng = np.random.RandomState(0)
    for elems in sweep:
        xs = (rng.randn(n, elems).astype(np.float32)
              * np.logspace(-1, 1, n)[:, None])
        x = jnp.asarray(xs.reshape(-1))

        flat_fn = jax.jit(shard_map(
            lambda v: jax.lax.pmean(v, "data"), mesh=mesh,
            in_specs=P("data"), out_specs=P(), check_vma=False))
        quant_fn = jax.jit(shard_map(
            lambda v: spmd.quantized_allreduce(v, "data", average=True,
                                               codec=codec),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False))

        flat_bytes = sum(collective_wire_bytes(
            flat_fn.lower(x).compile().as_text(), n).values())
        quant_bytes = sum(collective_wire_bytes(
            quant_fn.lower(x).compile().as_text(), n).values())
        reduction = flat_bytes / max(quant_bytes, 1)

        flat_out = np.asarray(flat_fn(x))
        quant_out = np.asarray(quant_fn(x))
        err = np.abs(quant_out - flat_out)
        # documented bound: per-element block absmax (across ranks) x
        # codec.ERROR_BOUND, over the codec's own block geometry
        block, padded = codec.block_layout(elems, n)
        absmax = np.zeros((n, padded), np.float32)
        absmax[:, :elems] = np.abs(xs)
        bmax = absmax.max(axis=0).reshape(-1, block).max(axis=1)
        bound = np.repeat(bmax * codec.ERROR_BOUND, block)[:elems]
        ok = bool((err <= bound + 1e-7).all())
        ratio = float((err / np.maximum(bound, 1e-30)).max())
        worst_err_ratio = max(worst_err_ratio, ratio)
        worst_reduction = reduction if worst_reduction is None else \
            min(worst_reduction, reduction)
        # Measured end-to-end wire SNR beside the analytic bound: the
        # actual quantized collective output vs the exact mean, through
        # the ONE accounting definition (obs.tensorwatch.snr_db — the
        # same formula the numerics observatory's in-job decode-SNR
        # gauges use, docs/tensorwatch.md). The bound column says what
        # the codec promises; this column says what THIS data measured.
        sig = float((flat_out.astype(np.float64) ** 2).sum())
        epow = float((err.astype(np.float64) ** 2).sum())
        measured_snr = snr_db(sig, epow)
        worst_snr = measured_snr if worst_snr is None \
            else min(worst_snr, measured_snr)
        print(f"{elems * 4 // 1024:>9}K {flat_bytes:>12} {quant_bytes:>12} "
              f"{reduction:>8.2f}x {err.max():>10.2e} {bound.max():>10.2e} "
              f"{measured_snr:>7.1f}dB {'y' if ok else 'N'}", flush=True)
        if not ok:
            print(f"AGREEMENT FAILURE at bucket {elems}: max err "
                  f"{err.max()} exceeds the documented bound", flush=True)
            sys.exit(1)

    # -- sparse top-k table (docs/compression.md §sparse) ------------------
    # Embedding-shaped workload: each rank's gradient touches a few hot
    # rows of a (vocab, dim) table hard and everything else barely — the
    # regime the top-k wire exists for. Beside the wire bytes (parsed
    # from the compiled HLO exactly like the dense rows) the table
    # reports wall-clock step time and the MEASURED end-to-end SNR next
    # to the analytic selection bound (min-over-ranks coverage through
    # ``TopKCompressor.roundtrip_error`` — the one accounting definition
    # the observatory's gauges use too).
    import time as _time

    from horovod_tpu.ops.compression import TopKCompressor

    vocab, dim = 8192, 32
    elems = vocab * dim
    hot_rows = max(vocab // 100, 1)
    emb = np.zeros((n, vocab, dim), np.float32)
    for d in range(n):
        rows = rng.choice(vocab, size=hot_rows, replace=False)
        emb[d, rows] = rng.randn(hot_rows, dim).astype(np.float32)
    emb += 1e-4 * rng.randn(n, vocab, dim).astype(np.float32)
    xs = emb.reshape(n, elems)
    x = jnp.asarray(xs.reshape(-1))

    flat_fn = jax.jit(shard_map(
        lambda v: jax.lax.pmean(v, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))
    flat_bytes = sum(collective_wire_bytes(
        flat_fn.lower(x).compile().as_text(), n).values())
    flat_out = np.asarray(flat_fn(x))

    def _timed(fn, arg, reps=5):
        fn(arg).block_until_ready()  # compile outside the clock
        t0 = _time.perf_counter()
        for _ in range(reps):
            fn(arg).block_until_ready()
        return (_time.perf_counter() - t0) / reps * 1e3

    flat_ms = _timed(flat_fn, x)
    print(f"# sparse top-k audit: embedding-shaped ({vocab}x{dim} table, "
          f"~{hot_rows} hot rows/rank), flat={flat_bytes} B/rank "
          f"@ {flat_ms:.2f} ms")
    print(f"{'k':>6} {'kept':>8} {'sparse B/rank':>13} {'reduction':>9} "
          f"{'step ms':>8} {'meas SNR':>9} {'cov bound':>9}")

    saved_key = TopKCompressor.FRACTION_KEY
    sparse_json = {}
    try:
        for key in sorted(TopKCompressor.FRACTIONS, key=float):
            TopKCompressor.set_fraction_key(key)
            sparse_fn = jax.jit(shard_map(
                lambda v: spmd.sparse_allreduce(
                    v, "data", average=True, codec=TopKCompressor),
                mesh=mesh, in_specs=P("data"), out_specs=P(),
                check_vma=False))
            sparse_bytes = sum(collective_wire_bytes(
                sparse_fn.lower(x).compile().as_text(), n).values())
            reduction = flat_bytes / max(sparse_bytes, 1)
            sparse_out = np.asarray(sparse_fn(x))
            err = sparse_out.astype(np.float64) - \
                flat_out.astype(np.float64)
            sig = float((flat_out.astype(np.float64) ** 2).sum())
            measured = snr_db(sig, float((err ** 2).sum()))
            # analytic selection bound: the worst rank's kept-energy
            # coverage, as the same dB the evidence gate certifies
            bound = min(snr_db(*TopKCompressor.roundtrip_error(xs[d], n))
                        for d in range(n))
            ms = _timed(sparse_fn, x)
            k = TopKCompressor.k_of(elems, key)
            print(f"{key + '%':>6} {k:>8} {sparse_bytes:>13} "
                  f"{reduction:>8.2f}x {ms:>8.2f} {measured:>7.1f}dB "
                  f"{bound:>7.1f}dB", flush=True)
            sparse_json[key] = {
                "wire_byte_reduction": round(reduction, 2),
                "step_time_ms": round(ms, 3),
                "measured_snr_db": round(measured, 2),
                "coverage_bound_db": round(bound, 2),
            }
    finally:
        TopKCompressor.FRACTION_KEY = saved_key

    print(json.dumps({
        "metric": f"{args.codec}_allreduce_wire_byte_reduction",
        "value": round(worst_reduction, 2),
        "unit": "x_vs_f32",
        "devices": n,
        "max_err_over_bound": round(worst_err_ratio, 3),
        "measured_snr_db_min": round(worst_snr, 2),
        "agreement_within_bound": True,
        "sparse_wire_byte_reduction": sparse_json["1"][
            "wire_byte_reduction"],
        "sparse_step_time_ms": sparse_json["1"]["step_time_ms"],
        "sparse_measured_snr_db": sparse_json["1"]["measured_snr_db"],
        "sparse_table": sparse_json,
    }), flush=True)


if __name__ == "__main__":
    main()
