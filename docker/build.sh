#!/bin/bash
# Build the horovod_tpu image (analog of the reference's
# build-docker-images.sh, which bakes its CUDA/MPI matrix).
set -euo pipefail
cd "$(dirname "$0")/.."
docker build -f docker/Dockerfile -t horovod_tpu:latest .
