"""Packaging + native-core build for horovod_tpu.

Rebuild of the reference's feature-probe build (``setup.py:84-141,477-592``)
for the TPU stack. The reference compiles its C++ common core into every
framework extension after probing the toolchain (C++ flags, AVX/F16C, MPI,
CUDA, NCCL, DDL) and honoring an env-var build matrix
(``HOROVOD_WITH[OUT]_*``, ``HOROVOD_GPU_ALLREDUCE``, ...). Here the data
plane is XLA — there is no MPI/CUDA/NCCL to probe — so the native surface
is the controller core (negotiator, GP/Bayesian autotuner, timeline
writer) built as one shared library, with:

* compiler flag probing (newest usable -std=, best -O level) in the spirit
  of ``get_cpp_flags`` (``setup.py:84-115``);
* an env-var matrix: ``HOROVOD_TPU_WITHOUT_NATIVE=1`` skips the native
  build (pure-Python fallbacks take over), ``HOROVOD_TPU_WITH_NATIVE=1``
  makes a native build failure fatal instead of a warning — the
  ``HOROVOD_WITH[OUT]_*`` semantics of ``setup.py:477-592``; ``CXX``
  overrides the compiler like ``HOROVOD_MPICXX_SHOW`` overrides mpicxx.

The library also self-builds lazily at import time (``horovod_tpu/cc``),
so setup.py is the packaging path, not the only path.
"""

import os
import subprocess
import sys
import tempfile
import textwrap

from setuptools import Command, setup
from setuptools.command.build_py import build_py

_ROOT = os.path.dirname(os.path.abspath(__file__))
_CC_DIR = os.path.join(_ROOT, "horovod_tpu", "cc")


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in ("", "0", "false")


def _compiler() -> str:
    return os.environ.get("CXX", "g++")


def probe_cxx_flags(cxx: str) -> list:
    """Pick the best supported flag set by compiling a probe program,
    mirroring the reference's test-compile loop (``setup.py:84-115``)."""
    probe = textwrap.dedent("""
        #include <memory>
        #include <thread>
        int main() { auto p = std::make_unique<int>(1); return *p - 1; }
    """)
    candidates = [
        ["-std=c++17", "-O3", "-fPIC", "-pthread"],
        ["-std=c++14", "-O2", "-fPIC", "-pthread"],
        ["-std=c++11", "-O2", "-fPIC", "-pthread"],
    ]
    with tempfile.TemporaryDirectory() as tmp:
        src = os.path.join(tmp, "probe.cc")
        with open(src, "w", encoding="utf-8") as fh:
            fh.write(probe)
        for flags in candidates:
            out = os.path.join(tmp, "probe.out")
            result = subprocess.run(
                [cxx, *flags, src, "-o", out],
                capture_output=True, text=True)
            if result.returncode == 0:
                return flags
    raise RuntimeError(
        f"{cxx} cannot compile C++11 or newer; set CXX to a working "
        f"compiler or HOROVOD_TPU_WITHOUT_NATIVE=1 to skip the native core.")


def _native_sources():
    """The Makefile's SRCS line is the single source of truth — a second
    hardcoded list here once shipped a library missing a translation unit."""
    with open(os.path.join(_CC_DIR, "Makefile"), encoding="utf-8") as fh:
        for line in fh:
            if line.startswith("SRCS"):
                return line.split(":=", 1)[1].split()
    raise RuntimeError("cc/Makefile has no SRCS line")


def build_native_core(out_dir: str) -> str:
    """Compile the native controller core into ``out_dir`` and return the
    library path."""
    cxx = _compiler()
    flags = probe_cxx_flags(cxx)
    os.makedirs(out_dir, exist_ok=True)
    lib = os.path.join(out_dir, "libhtpu_core.so")
    sources = [os.path.join(_CC_DIR, s) for s in _native_sources()]
    cmd = [cxx, *flags, "-Wall", "-Wextra", "-shared", "-o", lib, *sources]
    result = subprocess.run(cmd, capture_output=True, text=True)
    if result.returncode != 0:
        raise RuntimeError(
            f"native core build failed:\n$ {' '.join(cmd)}\n{result.stderr}")
    return lib


class BuildNative(Command):
    """``python setup.py build_native`` — standalone native-core build."""

    description = "build the native controller core (libhtpu_core.so)"
    user_options = []

    def initialize_options(self):  # noqa: D102
        pass

    def finalize_options(self):  # noqa: D102
        pass

    def run(self):  # noqa: D102
        if _env_flag("HOROVOD_TPU_WITHOUT_NATIVE"):
            print("HOROVOD_TPU_WITHOUT_NATIVE=1: skipping native core")
            return
        try:
            lib = build_native_core(os.path.join(_CC_DIR, "build"))
            print(f"built {lib}")
        except Exception as exc:  # noqa: BLE001
            if _env_flag("HOROVOD_TPU_WITH_NATIVE"):
                raise
            print(f"WARNING: native core unavailable, pure-Python fallbacks "
                  f"will be used: {exc}", file=sys.stderr)


class BuildPyWithNative(build_py):
    """Package build hook: compile the native core and ship it inside the
    package (the role of the reference's per-framework extension builders,
    ``setup.py:595-849``)."""

    def run(self):  # noqa: D102
        super().run()
        if _env_flag("HOROVOD_TPU_WITHOUT_NATIVE"):
            return
        target = os.path.join(self.build_lib, "horovod_tpu", "cc", "build")
        try:
            build_native_core(target)
        except Exception as exc:  # noqa: BLE001
            if _env_flag("HOROVOD_TPU_WITH_NATIVE"):
                raise
            print(f"WARNING: native core unavailable, pure-Python fallbacks "
                  f"will be used: {exc}", file=sys.stderr)


if __name__ == "__main__":
    setup(
        cmdclass={"build_native": BuildNative, "build_py": BuildPyWithNative},
    )
